mmlu_datasets = [
    {
        'abbr': 'lukaemon_mmlu_college_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college biology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college biology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college chemistry. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college chemistry. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college computer science. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college computer science. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about electrical engineering. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about electrical engineering. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about astronomy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about astronomy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about anatomy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about anatomy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about abstract algebra. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about abstract algebra. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about machine learning. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about machine learning. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about clinical knowledge. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about clinical knowledge. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about global facts. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about global facts. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about management. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about management. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about nutrition. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about nutrition. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about marketing. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about marketing. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional accounting. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional accounting. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school geography. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school geography. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about international law. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about international law. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral scenarios. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral scenarios. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about computer security. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about computer security. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school microeconomics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school microeconomics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional law. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional law. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about medical genetics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about medical genetics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional psychology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional psychology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about jurisprudence. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about jurisprudence. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about world religions. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about world religions. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about philosophy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about philosophy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about virology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about virology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school chemistry. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school chemistry. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about public relations. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about public relations. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school macroeconomics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school macroeconomics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human sexuality. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human sexuality. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about elementary mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about elementary mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school computer science. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school computer science. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school european history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school european history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about business ethics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about business ethics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral disputes. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral disputes. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school statistics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school statistics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about miscellaneous. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about miscellaneous. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about formal logic. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about formal logic. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school government and politics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school government and politics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about prehistory. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about prehistory. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about security studies. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about security studies. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school biology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school biology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about logical fallacies. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about logical fallacies. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school world history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school world history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional medicine. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional medicine. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college medicine. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college medicine. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school us history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school us history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about sociology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about sociology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about econometrics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about econometrics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school psychology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school psychology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human aging. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human aging. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about us foreign policy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about us foreign policy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about conceptual physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about conceptual physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    }
]
mmlu_ppl_datasets = [
    {
        'abbr': 'lukaemon_mmlu_college_biology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
mmlu_summary_groups = [
    {
        'name': 'mmlu',
        'subsets': [
            'lukaemon_mmlu_college_biology',
            'lukaemon_mmlu_college_chemistry',
            'lukaemon_mmlu_college_computer_science',
            'lukaemon_mmlu_college_mathematics',
            'lukaemon_mmlu_college_physics',
            'lukaemon_mmlu_electrical_engineering',
            'lukaemon_mmlu_astronomy',
            'lukaemon_mmlu_anatomy',
            'lukaemon_mmlu_abstract_algebra',
            'lukaemon_mmlu_machine_learning',
            'lukaemon_mmlu_clinical_knowledge',
            'lukaemon_mmlu_global_facts',
            'lukaemon_mmlu_management',
            'lukaemon_mmlu_nutrition',
            'lukaemon_mmlu_marketing',
            'lukaemon_mmlu_professional_accounting',
            'lukaemon_mmlu_high_school_geography',
            'lukaemon_mmlu_international_law',
            'lukaemon_mmlu_moral_scenarios',
            'lukaemon_mmlu_computer_security',
            'lukaemon_mmlu_high_school_microeconomics',
            'lukaemon_mmlu_professional_law',
            'lukaemon_mmlu_medical_genetics',
            'lukaemon_mmlu_professional_psychology',
            'lukaemon_mmlu_jurisprudence',
            'lukaemon_mmlu_world_religions',
            'lukaemon_mmlu_philosophy',
            'lukaemon_mmlu_virology',
            'lukaemon_mmlu_high_school_chemistry',
            'lukaemon_mmlu_public_relations',
            'lukaemon_mmlu_high_school_macroeconomics',
            'lukaemon_mmlu_human_sexuality',
            'lukaemon_mmlu_elementary_mathematics',
            'lukaemon_mmlu_high_school_physics',
            'lukaemon_mmlu_high_school_computer_science',
            'lukaemon_mmlu_high_school_european_history',
            'lukaemon_mmlu_business_ethics',
            'lukaemon_mmlu_moral_disputes',
            'lukaemon_mmlu_high_school_statistics',
            'lukaemon_mmlu_miscellaneous',
            'lukaemon_mmlu_formal_logic',
            'lukaemon_mmlu_high_school_government_and_politics',
            'lukaemon_mmlu_prehistory',
            'lukaemon_mmlu_security_studies',
            'lukaemon_mmlu_high_school_biology',
            'lukaemon_mmlu_logical_fallacies',
            'lukaemon_mmlu_high_school_world_history',
            'lukaemon_mmlu_professional_medicine',
            'lukaemon_mmlu_high_school_mathematics',
            'lukaemon_mmlu_college_medicine',
            'lukaemon_mmlu_high_school_us_history',
            'lukaemon_mmlu_sociology',
            'lukaemon_mmlu_econometrics',
            'lukaemon_mmlu_high_school_psychology',
            'lukaemon_mmlu_human_aging',
            'lukaemon_mmlu_us_foreign_policy',
            'lukaemon_mmlu_conceptual_physics'
        ]
    }
]
datasets = [
    {
        'abbr': 'lukaemon_mmlu_college_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college biology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college biology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college chemistry. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college chemistry. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college computer science. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college computer science. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about electrical engineering. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about electrical engineering. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about astronomy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about astronomy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about anatomy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about anatomy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about abstract algebra. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about abstract algebra. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about machine learning. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about machine learning. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about clinical knowledge. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about clinical knowledge. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about global facts. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about global facts. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about management. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about management. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about nutrition. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about nutrition. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about marketing. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about marketing. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional accounting. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional accounting. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school geography. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school geography. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about international law. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about international law. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral scenarios. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral scenarios. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about computer security. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about computer security. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school microeconomics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school microeconomics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional law. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional law. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about medical genetics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about medical genetics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional psychology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional psychology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about jurisprudence. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about jurisprudence. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about world religions. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about world religions. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about philosophy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about philosophy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about virology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about virology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school chemistry. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school chemistry. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about public relations. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about public relations. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school macroeconomics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school macroeconomics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human sexuality. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human sexuality. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about elementary mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about elementary mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school computer science. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school computer science. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school european history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school european history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about business ethics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about business ethics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral disputes. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about moral disputes. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school statistics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school statistics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about miscellaneous. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about miscellaneous. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about formal logic. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about formal logic. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school government and politics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school government and politics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about prehistory. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about prehistory. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about security studies. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about security studies. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school biology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school biology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about logical fallacies. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about logical fallacies. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school world history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school world history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional medicine. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about professional medicine. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school mathematics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school mathematics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college medicine. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about college medicine. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school us history. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school us history. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about sociology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about sociology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about econometrics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about econometrics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school psychology. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about high school psychology. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human aging. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about human aging. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about us foreign policy. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about us foreign policy. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about conceptual physics. Answer the question by replying A, B, C or D.\nQuestion: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{target}\n'
                        }
                    ]
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': 'There is a single choice question about conceptual physics. Answer the question by replying A, B, C or D.\nQ: {input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nA: '
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_biology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics_ppl',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
models = [
    {
        'type': 'opencompass_tpu.models.jax_lm.JaxLM',
        'abbr': 'llama-7b-jax',
        'path': './models/llama-7b-hf',
        'config': {
            'preset': 'llama'
        },
        'max_seq_len': 2048,
        'batch_size': 8,
        'max_out_len': 100,
        'dtype': 'bfloat16',
        'quantize': 'w8a8-kv4',
        'parallel': {
            'data': -1,
            'model': 1
        },
        'run_cfg': {
            'num_devices': 1
        }
    }
]
summarizer = {
    'summary_groups': [
        {
            'name': 'mmlu',
            'subsets': [
                'lukaemon_mmlu_college_biology',
                'lukaemon_mmlu_college_chemistry',
                'lukaemon_mmlu_college_computer_science',
                'lukaemon_mmlu_college_mathematics',
                'lukaemon_mmlu_college_physics',
                'lukaemon_mmlu_electrical_engineering',
                'lukaemon_mmlu_astronomy',
                'lukaemon_mmlu_anatomy',
                'lukaemon_mmlu_abstract_algebra',
                'lukaemon_mmlu_machine_learning',
                'lukaemon_mmlu_clinical_knowledge',
                'lukaemon_mmlu_global_facts',
                'lukaemon_mmlu_management',
                'lukaemon_mmlu_nutrition',
                'lukaemon_mmlu_marketing',
                'lukaemon_mmlu_professional_accounting',
                'lukaemon_mmlu_high_school_geography',
                'lukaemon_mmlu_international_law',
                'lukaemon_mmlu_moral_scenarios',
                'lukaemon_mmlu_computer_security',
                'lukaemon_mmlu_high_school_microeconomics',
                'lukaemon_mmlu_professional_law',
                'lukaemon_mmlu_medical_genetics',
                'lukaemon_mmlu_professional_psychology',
                'lukaemon_mmlu_jurisprudence',
                'lukaemon_mmlu_world_religions',
                'lukaemon_mmlu_philosophy',
                'lukaemon_mmlu_virology',
                'lukaemon_mmlu_high_school_chemistry',
                'lukaemon_mmlu_public_relations',
                'lukaemon_mmlu_high_school_macroeconomics',
                'lukaemon_mmlu_human_sexuality',
                'lukaemon_mmlu_elementary_mathematics',
                'lukaemon_mmlu_high_school_physics',
                'lukaemon_mmlu_high_school_computer_science',
                'lukaemon_mmlu_high_school_european_history',
                'lukaemon_mmlu_business_ethics',
                'lukaemon_mmlu_moral_disputes',
                'lukaemon_mmlu_high_school_statistics',
                'lukaemon_mmlu_miscellaneous',
                'lukaemon_mmlu_formal_logic',
                'lukaemon_mmlu_high_school_government_and_politics',
                'lukaemon_mmlu_prehistory',
                'lukaemon_mmlu_security_studies',
                'lukaemon_mmlu_high_school_biology',
                'lukaemon_mmlu_logical_fallacies',
                'lukaemon_mmlu_high_school_world_history',
                'lukaemon_mmlu_professional_medicine',
                'lukaemon_mmlu_high_school_mathematics',
                'lukaemon_mmlu_college_medicine',
                'lukaemon_mmlu_high_school_us_history',
                'lukaemon_mmlu_sociology',
                'lukaemon_mmlu_econometrics',
                'lukaemon_mmlu_high_school_psychology',
                'lukaemon_mmlu_human_aging',
                'lukaemon_mmlu_us_foreign_policy',
                'lukaemon_mmlu_conceptual_physics'
            ]
        },
        {
            'name': 'mmlu_ppl',
            'subsets': [
                'lukaemon_mmlu_college_biology_ppl',
                'lukaemon_mmlu_college_chemistry_ppl',
                'lukaemon_mmlu_college_computer_science_ppl',
                'lukaemon_mmlu_college_mathematics_ppl',
                'lukaemon_mmlu_college_physics_ppl',
                'lukaemon_mmlu_electrical_engineering_ppl',
                'lukaemon_mmlu_astronomy_ppl',
                'lukaemon_mmlu_anatomy_ppl',
                'lukaemon_mmlu_abstract_algebra_ppl',
                'lukaemon_mmlu_machine_learning_ppl',
                'lukaemon_mmlu_clinical_knowledge_ppl',
                'lukaemon_mmlu_global_facts_ppl',
                'lukaemon_mmlu_management_ppl',
                'lukaemon_mmlu_nutrition_ppl',
                'lukaemon_mmlu_marketing_ppl',
                'lukaemon_mmlu_professional_accounting_ppl',
                'lukaemon_mmlu_high_school_geography_ppl',
                'lukaemon_mmlu_international_law_ppl',
                'lukaemon_mmlu_moral_scenarios_ppl',
                'lukaemon_mmlu_computer_security_ppl',
                'lukaemon_mmlu_high_school_microeconomics_ppl',
                'lukaemon_mmlu_professional_law_ppl',
                'lukaemon_mmlu_medical_genetics_ppl',
                'lukaemon_mmlu_professional_psychology_ppl',
                'lukaemon_mmlu_jurisprudence_ppl',
                'lukaemon_mmlu_world_religions_ppl',
                'lukaemon_mmlu_philosophy_ppl',
                'lukaemon_mmlu_virology_ppl',
                'lukaemon_mmlu_high_school_chemistry_ppl',
                'lukaemon_mmlu_public_relations_ppl',
                'lukaemon_mmlu_high_school_macroeconomics_ppl',
                'lukaemon_mmlu_human_sexuality_ppl',
                'lukaemon_mmlu_elementary_mathematics_ppl',
                'lukaemon_mmlu_high_school_physics_ppl',
                'lukaemon_mmlu_high_school_computer_science_ppl',
                'lukaemon_mmlu_high_school_european_history_ppl',
                'lukaemon_mmlu_business_ethics_ppl',
                'lukaemon_mmlu_moral_disputes_ppl',
                'lukaemon_mmlu_high_school_statistics_ppl',
                'lukaemon_mmlu_miscellaneous_ppl',
                'lukaemon_mmlu_formal_logic_ppl',
                'lukaemon_mmlu_high_school_government_and_politics_ppl',
                'lukaemon_mmlu_prehistory_ppl',
                'lukaemon_mmlu_security_studies_ppl',
                'lukaemon_mmlu_high_school_biology_ppl',
                'lukaemon_mmlu_logical_fallacies_ppl',
                'lukaemon_mmlu_high_school_world_history_ppl',
                'lukaemon_mmlu_professional_medicine_ppl',
                'lukaemon_mmlu_high_school_mathematics_ppl',
                'lukaemon_mmlu_college_medicine_ppl',
                'lukaemon_mmlu_high_school_us_history_ppl',
                'lukaemon_mmlu_sociology_ppl',
                'lukaemon_mmlu_econometrics_ppl',
                'lukaemon_mmlu_high_school_psychology_ppl',
                'lukaemon_mmlu_human_aging_ppl',
                'lukaemon_mmlu_us_foreign_policy_ppl',
                'lukaemon_mmlu_conceptual_physics_ppl'
            ]
        }
    ]
}
infer = {
    'partitioner': {
        'type': 'SizePartitioner',
        'max_task_size': 40000,
        'gen_task_coef': 20
    }
}
task_timeout = 14400
stall_timeout = 1800
work_dir = './outputs/llama_7b_mmlu/20260731_041540'
