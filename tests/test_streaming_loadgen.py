"""ISSUE 20 front door: SSE token streaming, the elastic autoscaler's
policy core, hot-prefix pinning, and the replay load generator.

Unit tier: CompletionStreamSession delivery/identity/disconnect
semantics, the pure ``decide()`` hysteresis+cooldown policy,
``WorkerPool.retire_excess``, ``HotPrefixPinner`` bookkeeping, and the
loadgen trace/arrival/summary math — all daemonless.

Live tier (tier-1, one shared chaos daemon like
``test_quick_scenarios_live``): streamed and buffered responses are
token-identical with a *measured* first-byte ``ttft_s``, and a client
that hangs up mid-stream leaves a ``degraded: client_disconnect``
record while the daemon stays healthy."""
import json
import os.path as osp
import socket
import time

import pytest

from opencompass_tpu.serve.autoscaler import (AutoscalerConfig,
                                              KeyState, decide,
                                              instance_key)
from opencompass_tpu.serve.pinner import HotPrefixPinner
from opencompass_tpu.serve.stream import (SSE_DONE,
                                          CompletionStreamSession,
                                          sse_event)


def _events(sends):
    """Decode a list of raw SSE byte frames into payload dicts."""
    out = []
    for raw in sends:
        if raw == SSE_DONE:
            out.append('[DONE]')
            continue
        assert raw.startswith(b'data: ') and raw.endswith(b'\n\n')
        out.append(json.loads(raw[len(b'data: '):].decode('utf-8')))
    return out


def _chunk_text(events):
    return ''.join(c.get('text') or ''
                   for e in events if isinstance(e, dict)
                   for c in e.get('choices') or [])


# -- CompletionStreamSession ------------------------------------------------

def test_stream_session_tail_makes_concat_identical():
    """finish() emits only each row's unstreamed tail, so the streamed
    concatenation equals the buffered text whether zero, some, or all
    pieces arrived as interim frames."""
    sends = []
    s = CompletionStreamSession('cmpl-x', 'm')
    s.bind_send(sends.append)
    s.on_frame({'row': 0, 'piece': 'tok '})
    s.on_frame({'row': 0, 'piece': 'tok '})
    s.finish({'completions': ['tok tok tok '], 'prompt_tokens': 2,
              'completion_tokens': 3})
    events = _events(sends)
    assert events[-1] == '[DONE]'
    assert _chunk_text(events) == 'tok tok tok '
    final = events[-2]
    assert final['usage']['total_tokens'] == 5
    # stream_frames is stamped when the summary chunk is BUILT, i.e.
    # before its own delivery bumps the counter
    assert final['oct']['stream_frames'] == s.frames - 1
    # delivery-side truth: measured first byte, ITL between frames
    assert s.first_byte_s is not None and s.first_byte_s >= 0
    assert len(s.itl_s) == 3   # 4 delivered frames -> 3 gaps
    assert s.record_fields()['frames'] == 4

    # dense path: no interim frames at all, whole text rides the tail
    sends2 = []
    s2 = CompletionStreamSession('cmpl-y', 'm')
    s2.bind_send(sends2.append)
    s2.finish({'completions': ['whole answer']})
    assert _chunk_text(_events(sends2)) == 'whole answer'
    assert s2.first_byte_s is not None


def test_stream_session_disconnect_fires_abort_once_bound():
    from opencompass_tpu.obs.promexport import ClientDisconnected

    def dead_send(_chunk):
        raise ClientDisconnected('gone')

    aborts = []
    s = CompletionStreamSession('cmpl-z', 'm')
    s.bind_send(dead_send)
    s.on_frame({'row': 0, 'piece': 'tok '})   # send raises -> mark dead
    assert s.disconnected
    # abort bound AFTER the disconnect must fire immediately
    s.bind_abort(lambda: aborts.append(1))
    assert aborts == [1]
    # further frames are dropped without touching the socket
    s.on_frame({'row': 0, 'piece': 'tok '})
    s.finish({'completions': ['tok tok ']})
    fields = s.record_fields()
    assert fields['disconnected'] and fields['frames'] == 0


def test_stream_session_error_event_shape():
    sends = []
    s = CompletionStreamSession('cmpl-e', 'm')
    s.bind_send(sends.append)
    s.send_error('budget exhausted', 'deadline_exceeded',
                 phase='model_forward')
    events = _events(sends)
    assert events[-1] == '[DONE]'
    assert events[0]['object'] == 'error'
    assert events[0]['error']['type'] == 'deadline_exceeded'
    assert events[0]['error']['phase'] == 'model_forward'


def test_sse_event_single_line_framing():
    raw = sse_event({'a': 1, 'b': 'x\ny'})   # newline survives as \n
    assert raw.startswith(b'data: ') and raw.endswith(b'\n\n')
    assert raw.count(b'\n') == 2   # JSON stays single-line


# -- autoscaler policy core -------------------------------------------------

def test_decide_hysteresis_cooldowns_and_bounds():
    cfg = AutoscalerConfig(min_replicas=1, max_replicas=3,
                           up_consecutive=2, down_consecutive=3,
                           scale_up_cooldown_s=10.0,
                           scale_down_cooldown_s=20.0,
                           up_queue_eta_s=5.0, up_slot_util=0.8,
                           down_slot_util=0.2)
    st = KeyState(replicas=1)
    hot = {'queue_eta_s': 9.0}
    calm = {'queue_eta_s': 0.0, 'slot_util': 0.0, 'inflight': 0}

    # one pressure read is not enough (hysteresis)
    assert decide(hot, cfg, st, now=0.0) is None
    d = decide(hot, cfg, st, now=1.0)
    assert d and d['direction'] == 'up' and (d['from'], d['to']) == (1, 2)
    assert d['reason'] == 'queue_eta'
    # streak reset + cooldown: two more hot reads inside the window -> no
    assert decide(hot, cfg, st, now=2.0) is None
    assert decide(hot, cfg, st, now=3.0) is None
    # past the up-cooldown the streak is satisfied again
    d2 = decide(hot, cfg, st, now=12.0)
    assert d2 and d2['to'] == 3
    # at max_replicas pressure can never scale further
    assert decide(hot, cfg, st, now=30.0) is None
    assert decide(hot, cfg, st, now=31.0) is None

    # idle shrinks only after down_consecutive calm reads AND the
    # down-after-up guard (one full up-cooldown) has passed
    for now in (32.0, 33.0):
        assert decide(calm, cfg, st, now=now) is None
    d3 = decide(calm, cfg, st, now=34.0)
    assert d3 and d3['direction'] == 'down' and d3['to'] == 2
    # down cooldown holds the next shrink
    for now in (35.0, 36.0, 37.0, 38.0):
        assert decide(calm, cfg, st, now=now) is None
    d4 = decide(calm, cfg, st, now=60.0)
    assert d4 and d4['to'] == 1
    # at min_replicas idleness never shrinks further
    for now in (61.0, 62.0, 63.0, 90.0):
        assert decide(calm, cfg, st, now=now) is None
    assert st.replicas == 1


def test_decide_mixed_signal_resets_streaks_and_inflight_blocks_down():
    cfg = AutoscalerConfig(up_consecutive=2, down_consecutive=2,
                           up_slot_util=0.8, down_slot_util=0.3)
    st = KeyState(replicas=2)
    # busy-but-not-pressured (mid utilization) is neither hot nor idle
    assert decide({'slot_util': 0.5}, cfg, st, now=0.0) is None
    assert st.up_streak == 0 and st.down_streak == 0
    # calm utilization but a held admission seat blocks the idle path
    seat = {'slot_util': 0.0, 'inflight': 1, 'queue_eta_s': 0.0}
    for now in (1.0, 2.0, 3.0):
        assert decide(seat, cfg, st, now=now) is None
    assert st.down_streak == 0


def test_decide_breaker_open_is_pressure():
    cfg = AutoscalerConfig(up_consecutive=1, max_replicas=2)
    st = KeyState(replicas=1)
    d = decide({'breakers_open': 1}, cfg, st, now=0.0)
    assert d and d['reason'] == 'breaker_open' and d['to'] == 2


def test_autoscaler_config_validation_and_instance_keys():
    assert AutoscalerConfig.from_cfg(None) is None
    with pytest.raises(ValueError, match='unknown autoscaler key'):
        AutoscalerConfig.from_cfg({'max_replicas': 2, 'bogus': 1})
    with pytest.raises(ValueError, match='must be a dict'):
        AutoscalerConfig.from_cfg([1])
    cfg = AutoscalerConfig.from_cfg({'min_replicas': 2,
                                     'max_replicas': 1})
    assert cfg.max_replicas >= cfg.min_replicas
    assert instance_key('k', 0) == 'k'          # replica 0 IS the key
    assert instance_key('k', 2) == 'k@r2'


# -- WorkerPool.retire_excess ----------------------------------------------

class _FakeHandle:
    spawned = []

    def __init__(self, env, log_path):
        self.env, self.log_path = env, log_path
        self.dead = False
        self.proc = type('P', (), {
            'pid': 4242, 'poll': staticmethod(lambda: None)})()
        self.shutdowns = 0
        _FakeHandle.spawned.append(self)

    def request(self, msg, timeout=None):
        return {'ok': True}

    def request_watched(self, msg, **kw):
        return self.request(msg)

    def shutdown(self, timeout=10.0):
        self.shutdowns += 1
        self.dead = True
        self.proc.poll = lambda: 0

    def kill(self):
        self.dead = True
        self.proc.poll = lambda: 0


@pytest.fixture()
def fake_worker(monkeypatch):
    from opencompass_tpu.runners import worker as workermod
    _FakeHandle.spawned = []
    monkeypatch.setattr(workermod, 'WorkerHandle', _FakeHandle)
    return _FakeHandle


def _spawn(chip_ids):
    return {'CHIPS': ','.join(map(str, chip_ids))}, '/dev/null'


def test_retire_excess_keeps_base_and_leased_replicas(fake_worker):
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=None)
    base = pool.acquire('m1', _spawn)
    r1 = pool.acquire('m1@r1', _spawn)
    r2 = pool.acquire('m1@r2', _spawn)
    pool.acquire('other@r1', _spawn)      # different base key: untouched
    pool.release(base)
    pool.release(r2)                       # r1 stays leased
    retired = pool.retire_excess('m1', keep=1)
    assert retired == ['m1@r2']            # r1 leased, base never retired
    assert r2.handle.shutdowns == 1
    pool.release(r1)
    assert pool.retire_excess('m1', keep=1) == ['m1@r1']
    # keep clamps at 1: replica 0 (the bare key) is not an @r instance
    assert pool.retire_excess('m1', keep=0) == []
    assert pool.resident_count == 2        # m1 + other@r1
    pool.shutdown()


# -- hot-prefix pinner ------------------------------------------------------

def test_pinner_pins_hot_prefix_and_unpins_lru():
    p = HotPrefixPinner(min_count=3, max_pinned=2, prefix_chars=8)
    sys_a, sys_b, sys_c = 'AAAAAAAA-x', 'BBBBBBBB-y', 'CCCCCCCC-z'
    assert p.observe('k', [sys_a], now=1.0) == ([], [])
    assert p.observe('k', [sys_a], now=2.0) == ([], [])
    to_pin, to_unpin = p.observe('k', [sys_a], now=3.0)
    assert to_pin == [sys_a[:8]] and not to_unpin
    # a pinned prefix refreshes recency instead of recounting
    assert p.observe('k', [sys_a], now=10.0) == ([], [])
    for now in (4.0, 5.0, 6.0):
        p.observe('k', [sys_b], now=now)
    # third distinct hot prefix displaces the LRU one (sys_b: older)
    for now in (7.0, 8.0):
        p.observe('k', [sys_c], now=now)
    to_pin, to_unpin = p.observe('k', [sys_c], now=9.0)
    assert to_pin == [sys_c[:8]]
    assert to_unpin == [sys_b[:8]]
    snap = p.snapshot()
    assert snap['pinned'] == {'k': 2}
    assert snap['pins'] == 3 and snap['unpins'] == 1
    # counts only — never raw prompt text
    assert sys_a[:8] not in json.dumps(snap)


def test_pinner_bounds_candidate_table():
    p = HotPrefixPinner(min_count=99, max_pinned=1, prefix_chars=64)
    for i in range(200):
        p.observe('k', [f'unique prompt {i:04d}'], now=float(i))
    assert len(p._counts['k']) <= 64 * p.max_pinned


# -- loadgen math -----------------------------------------------------------

def test_load_trace_reads_access_shaped_rows(tmp_path):
    from opencompass_tpu.loadgen.replay import load_trace
    path = tmp_path / 'access.jsonl'
    rows = [
        {'v': 1, 'ts': 30.0, 'method': 'POST',
         'path': '/v1/completions', 'status': 200, 'model': 'm'},
        {'v': 1, 'ts': 10.0, 'method': 'POST',
         'path': '/v1/completions', 'status': 200, 'model': 'm'},
        {'v': 1, 'ts': 20.0, 'method': 'GET', 'path': '/healthz'},
        {'ts': 15.0, 'prompt': 'hand-written row', 'model': 'm',
         'max_tokens': 4},
    ]
    path.write_text('\n'.join(json.dumps(r) for r in rows) + '\n')
    specs = load_trace(str(path))
    # completions + prompt-bearing rows only, sorted by ts
    assert [s['ts'] for s in specs] == [10.0, 15.0, 30.0]
    assert specs[1]['prompt'] == 'hand-written row'
    assert specs[1]['max_tokens'] == 4
    # promptless rows synthesize distinct prompts
    assert specs[0]['prompt'] != specs[2]['prompt']
    # rows with no model anywhere are skipped; --model fills the gap
    path2 = tmp_path / 'nomodel.jsonl'
    path2.write_text(json.dumps({'ts': 1.0, 'method': 'POST',
                                 'path': '/v1/completions'}) + '\n')
    assert load_trace(str(path2)) == []
    assert load_trace(str(path2), model='m')[0]['model'] == 'm'


def test_build_arrivals_replay_compression_and_poisson_determinism():
    from opencompass_tpu.loadgen.replay import (build_arrivals,
                                                synth_trace)
    trace = synth_trace(5, 'm', rate=0.5)        # ts: 0, 2, 4, 6, 8
    replayed = build_arrivals(trace, mode='replay', speedup=4.0)
    assert replayed == [0.0, 0.5, 1.0, 1.5, 2.0]
    a = build_arrivals(trace, mode='poisson', speedup=10.0, seed=7)
    b = build_arrivals(trace, mode='poisson', speedup=10.0, seed=7)
    assert a == b and a[0] == 0.0                # seeded => identical
    assert a != build_arrivals(trace, mode='poisson', speedup=10.0,
                               seed=8)
    # mean gap ~ 1/(base_rate*speedup) = 0.2s: sanity-band the span
    assert 0.05 < a[-1] / (len(a) - 1) < 1.0
    with pytest.raises(ValueError, match='unknown arrival mode'):
        build_arrivals(trace, mode='uniform')
    assert build_arrivals([], mode='replay') == []


def test_synth_trace_prefix_and_distinct_cycle():
    from opencompass_tpu.loadgen.replay import synth_trace
    trace = synth_trace(4, 'm', rate=2.0, distinct=2, prefix='Q: row')
    assert [s['ts'] for s in trace] == [0.0, 0.5, 1.0, 1.5]
    assert trace[0]['prompt'].startswith('Q: row')
    assert trace[0]['prompt'] == trace[2]['prompt']   # cycle of 2
    assert trace[0]['prompt'] != trace[1]['prompt']


def test_summarize_percentiles_and_status_counts():
    from opencompass_tpu.loadgen.replay import summarize
    results = [
        {'status': 200, 'ok': True, 'ttft_s': 0.010,
         'itl_s': [0.004, 0.006], 'latency_s': 0.1, 'frames': 3,
         'chars': 12},
        {'status': 200, 'ok': True, 'ttft_s': 0.030, 'itl_s': [0.008],
         'latency_s': 0.2, 'frames': 2, 'chars': 8},
        {'status': 429, 'ok': False, 'ttft_s': None, 'itl_s': [],
         'frames': 0, 'chars': 0},
        {'status': 0, 'ok': False, 'error': 'boom', 'frames': 0,
         'chars': 0},
    ]
    rep = summarize(results, wall_s=2.0)
    assert rep['requests'] == 4 and rep['completed'] == 2
    assert rep['errors'] == 2
    assert rep['status_counts'] == {'200': 2, '429': 1,
                                    'transport': 1}
    assert rep['sustained_rps'] == 1.0
    assert rep['frames_total'] == 5 and rep['chars_total'] == 20
    assert rep['ttft_ms']['p50'] == 10.0
    assert rep['ttft_ms']['p99'] == 30.0 and rep['ttft_ms']['n'] == 2
    assert rep['itl_ms']['p99'] == 8.0
    empty = summarize([], wall_s=0.0)
    assert empty['sustained_rps'] is None
    assert empty['ttft_ms']['p50'] is None


def test_loadgen_cli_check_on_dead_target(capsys):
    """Nothing listening: every request is a transport error and
    --check exits non-zero with the report still printed."""
    from opencompass_tpu.loadgen.cli import main
    rc = main(['--target', 'http://127.0.0.1:9', '--model', 'm',
               '--requests', '2', '--rate', '50', '--timeout', '2',
               '--check'])
    assert rc != 0
    rep = json.loads(capsys.readouterr().out)
    assert rep['completed'] == 0 and rep['errors'] == 2


# -- live daemon: streaming identity + disconnect cleanup -------------------

@pytest.fixture(scope='module')
def live_daemon(tmp_path_factory):
    from opencompass_tpu.analysis.chaos import ChaosDaemon
    workdir = tmp_path_factory.mktemp('stream-daemon')
    daemon = ChaosDaemon(str(workdir), max_inflight=4)
    daemon.start()
    yield daemon
    daemon.stop()


def _read_sse(host, port, body, close_after_frames=None, timeout=60.0):
    """Minimal SSE client over a raw socket: returns (status, events).
    With ``close_after_frames`` it RST-closes the connection once that
    many data events arrived (the mid-stream hang-up)."""
    payload = json.dumps(body).encode('utf-8')
    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        sock.sendall(
            b'POST /v1/completions HTTP/1.1\r\n'
            + f'Host: {host}:{port}\r\n'.encode()
            + f'Content-Length: {len(payload)}\r\n'.encode()
            + b'Content-Type: application/json\r\n\r\n' + payload)
        buf = b''
        while b'\r\n\r\n' not in buf:
            buf += sock.recv(4096)
        head, buf = buf.split(b'\r\n\r\n', 1)
        status = int(head.split(b' ', 2)[1])
        events = []
        while True:
            while b'\n\n' not in buf:
                chunk = sock.recv(4096)
                if not chunk:
                    return status, events
                buf += chunk
            frame, buf = buf.split(b'\n\n', 1)
            for line in frame.splitlines():
                if not line.startswith(b'data: '):
                    continue
                data = line[len(b'data: '):]
                if data == b'[DONE]':
                    return status, events
                events.append(json.loads(data.decode('utf-8')))
            if close_after_frames is not None \
                    and len(events) >= close_after_frames:
                # RST on close: the daemon's next flush must fail
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                b'\x01\x00\x00\x00\x00\x00\x00\x00')
                return status, events
    finally:
        sock.close()


def test_streamed_identical_to_buffered_with_measured_ttft(live_daemon):
    """Acceptance: streamed and non-streamed greedy responses are
    token-identical, and the streamed record's ttft_s is a measured
    first-byte delivery timestamp, not the estimate."""
    from opencompass_tpu.utils.fileio import iter_jsonl_records
    host, port = '127.0.0.1', int(live_daemon.base.rsplit(':', 1)[1])
    prompt = 'Q: stream identity check'
    status, events = _read_sse(
        host, port, {'model': 'fake-chaos', 'prompt': prompt,
                     'max_tokens': 8, 'stream': True})
    assert status == 200
    streamed_text = _chunk_text(events)
    final = events[-1]
    assert final['oct']['stream_frames'] >= 2   # engine-paced pieces
    assert final['oct']['ttft_seconds'] is not None
    assert final['usage']['completion_tokens'] is not None
    cmpl_id = final['oct']['id']

    buffered = live_daemon.request(prompt, max_tokens=8)
    assert buffered.code == 200
    buffered_text = buffered.payload['choices'][0]['text']
    assert streamed_text == buffered_text and streamed_text.strip()

    # the durable record: measured first-byte ttft + stream counters
    req_path = osp.join(live_daemon.serve_obs_dir, 'requests.jsonl')
    rec = None
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and rec is None:
        rec = next((r for r in iter_jsonl_records(req_path)
                    if r.get('id') == cmpl_id), None)
        time.sleep(0.2)
    assert rec, f'no record for {cmpl_id}'
    assert rec['ttft_source'] == 'stream_first_byte'
    assert rec['ttft_s'] == pytest.approx(
        final['oct']['ttft_seconds'], abs=1e-6)
    assert 'ttft_estimated' not in rec
    # the record is cut when the worker round-trip returns (before the
    # summary chunk ships), so it counts the interim frames
    assert 2 <= rec['stream']['frames'] \
        <= final['oct']['stream_frames']
    assert not rec['stream']['disconnected']


def test_client_disconnect_aborts_and_records(live_daemon):
    """Regression: a consumer hanging up mid-stream must cancel the
    engine rows (no slot leak) and land a ``degraded:
    client_disconnect`` record — and the daemon keeps serving."""
    from opencompass_tpu.utils.fileio import iter_jsonl_records
    host, port = '127.0.0.1', int(live_daemon.base.rsplit(':', 1)[1])
    status, events = _read_sse(
        host, port, {'model': 'fake-chaos',
                     'prompt': 'Q: disconnect me', 'max_tokens': 8,
                     'stream': True},
        close_after_frames=1)
    assert status == 200 and len(events) >= 1

    req_path = osp.join(live_daemon.serve_obs_dir, 'requests.jsonl')
    rec = None
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and rec is None:
        rec = next(
            (r for r in iter_jsonl_records(req_path)
             if r.get('degraded') == 'client_disconnect'), None)
        time.sleep(0.2)
    assert rec, 'no client_disconnect record after the hang-up'
    assert rec['stream']['disconnected']
    # availability SLO must not count the client's own hang-up
    assert rec.get('slo_excluded') or rec.get('status') != 'error'
    # daemon healthy and still serving afterwards
    assert live_daemon.health().code == 200
    after = live_daemon.request('Q: after disconnect', max_tokens=4)
    assert after.code == 200
