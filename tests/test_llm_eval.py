"""LLM-judge ModelEvaluator: ranking parse, Borda aggregation, robustness."""
import json
import os.path as osp

import pytest

from opencompass_tpu.models import FakeModel
from opencompass_tpu.tasks import ModelEvaluator


class RankingJudge(FakeModel):
    """Judge that always prefers answers containing 'good'."""

    def generate(self, inputs, max_out_len):
        out = []
        for prompt in inputs:
            answers = [line for line in str(prompt).splitlines()
                       if line.startswith('A')]
            order = sorted(range(len(answers)),
                           key=lambda i: 'good' in answers[i])  # worst first
            out.append(' '.join(str(i) for i in order))
        return out


def _write_preds(work_dir, model_abbr, dataset_abbr, preds):
    d = work_dir / 'predictions' / model_abbr
    d.mkdir(parents=True, exist_ok=True)
    (d / f'{dataset_abbr}.json').write_text(json.dumps({
        str(i): {'origin_prompt': f'question {i}?', 'prediction': p}
        for i, p in enumerate(preds)
    }))


def test_model_evaluator_ranks_models(tmp_path):
    _write_preds(tmp_path, 'model-a', 'ds', ['good answer'] * 4)
    _write_preds(tmp_path, 'model-b', 'ds', ['bad answer'] * 4)
    ev = ModelEvaluator({
        'models': [{'abbr': 'model-a'}, {'abbr': 'model-b'}],
        'datasets': [{'abbr': 'ds'}],
        'work_dir': str(tmp_path),
        'evaluator': {'judger': RankingJudge()},
    })
    results = ev.evaluate()
    scores = results['ds']['scores']
    assert scores['model-a'] == 100.0  # always best
    assert scores['model-b'] == 0.0
    assert results['ds']['judged'] == 4
    assert osp.exists(tmp_path / 'results' / 'llm_judge' / 'ds.json')


def test_model_evaluator_skips_malformed_judgments(tmp_path):
    _write_preds(tmp_path, 'm0', 'ds', ['x'] * 3)
    _write_preds(tmp_path, 'm1', 'ds', ['y'] * 3)
    judge = FakeModel(canned_responses={'Q:': 'no digits here'})
    ev = ModelEvaluator({
        'models': [{'abbr': 'm0'}, {'abbr': 'm1'}],
        'datasets': [{'abbr': 'ds'}],
        'work_dir': str(tmp_path),
        'evaluator': {'judger': judge},
    })
    assert ev.evaluate() == {}  # everything skipped, no crash


def test_model_evaluator_needs_two_models(tmp_path):
    with pytest.raises(ValueError, match='two models'):
        ModelEvaluator({
            'models': [{'abbr': 'only'}],
            'datasets': [],
            'work_dir': str(tmp_path),
            'evaluator': {'judger': FakeModel()},
        })


def test_parse_ranking():
    ev = ModelEvaluator.__new__(ModelEvaluator)
    assert ev._parse_ranking('1 0 2', 3) == [1, 0, 2]
    assert ev._parse_ranking('ranking: 2, 1, 0.', 3) == [2, 1, 0]
    assert ev._parse_ranking('0 0 1', 3) is None   # not a permutation
    assert ev._parse_ranking('0 1', 3) is None     # too short
    assert ev._parse_ranking('garbage', 2) is None


def test_collect_env():
    from opencompass_tpu.utils.collect_env import collect_env
    info = collect_env()
    assert 'jax' in info and 'opencompass_tpu' in info
    assert info['Python']
