"""Hardware parity test for the Pallas flash-attention kernel.

The reference never needed this — torch/`transformers` owned attention
(reference opencompass/models/huggingface.py:201-226).  Our kernel
(nn/flash.py) is on the PPL hot path whenever shapes allow, so its numerics
must match the reference `_attention` einsum path on the actual TPU.

The main test suite runs on a hermetic CPU mesh (conftest.py), where the
kernel never executes — so this test launches a subprocess with the TPU
plugin env restored and compares full-model logits with flash on vs off on
a ragged (padded) batch.  Skipped when no TPU is available.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import sys
sys.path.insert(0, %(repo)r)
import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == 'tpu', jax.devices()

from opencompass_tpu.nn import (TransformerConfig, forward, init_params,
                                sequence_nll)
from opencompass_tpu.nn.flash import flash_supported

# flash-eligible geometry: head_dim 128, seq 256 (block 256)
cfg = TransformerConfig.llama(
    vocab_size=1024, hidden_size=512, num_layers=2, num_heads=4,
    num_kv_heads=2, intermediate_size=1024, max_seq_len=256)
assert flash_supported(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, 256)

params = init_params(cfg, jax.random.PRNGKey(0))
rng = np.random.RandomState(0)
B, S = 4, 256
tokens = jnp.asarray(rng.randint(0, 1024, (B, S)), jnp.int32)
# ragged right-padding, incl. one full row and one mostly-pad row
lens = [256, 200, 97, 5]
mask = jnp.asarray(np.arange(S)[None, :] < np.array(lens)[:, None])

logits_flash = jax.jit(
    lambda p, t, m: forward(p, cfg, t, m, use_flash=True))(
        params, tokens, mask)
logits_ref = jax.jit(
    lambda p, t, m: forward(p, cfg, t, m, use_flash=False))(
        params, tokens, mask)

lf = np.asarray(logits_flash, np.float32)
lr = np.asarray(logits_ref, np.float32)
m = np.asarray(mask)
# compare only real positions (pad rows see garbage-vs-garbage)
diff = np.abs(lf - lr)[m]
scale = np.abs(lr)[m].max()
print('max_abs_diff', diff.max(), 'scale', scale)
assert diff.max() <= 0.12, (diff.max(), scale)

nll_f = np.asarray(sequence_nll(logits_flash, tokens, mask))
nll_r = np.asarray(sequence_nll(logits_ref, tokens, mask))
np.testing.assert_allclose(nll_f, nll_r, rtol=2e-2, atol=2e-2)
print('FLASH_PARITY_OK')
"""


@pytest.mark.slow
def test_flash_matches_reference_attention_on_tpu():
    axon = os.environ.get('OC_TPU_AXON_IPS')
    if not axon:
        pytest.skip('no TPU plugin config in environment')
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = axon
    env.pop('JAX_PLATFORMS', None)
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run(
        [sys.executable, '-c', _SCRIPT % {'repo': REPO}],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert 'FLASH_PARITY_OK' in proc.stdout, proc.stdout
