"""Beam search decode (nn/decode.py beam_generate).

Covers the reference's beam decoding strategy (reference
opencompass/models/glm.py:166-285) rebuilt as a static-shape jitted
while_loop.  Properties pinned here:

- num_beams=1 reproduces greedy decoding exactly (same argmax chain).
- The selected hypothesis never scores below greedy's under the model
  (beam search widens the search; with length_penalty=1 and no EOS both
  paths emit full-length sequences, so summed logprob is comparable).
- On an enumerable toy problem, beam search with nb >= vocab_size finds
  the true best sequence (exhaustive-search cross-check).
- EOS freezes a beam: everything after the first EOS is pad.
- JaxLM plumbs generation_kwargs num_beams through.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.models import JaxLM
from opencompass_tpu.nn import (TransformerConfig, beam_generate, forward,
                                greedy_generate, init_params)

CFG = TransformerConfig.tiny()


def _data(B=2, S=12, seed=3):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (B, S), 0, CFG.vocab_size)
    return tokens, jnp.ones((B, S), bool)


def _seq_score(params, cfg, prompt, pmask, cont):
    """Summed logprob of `cont` (B, T) given `prompt` under the model."""
    full = jnp.concatenate([prompt, cont], axis=1)
    mask = jnp.concatenate([pmask, jnp.ones_like(cont, bool)], axis=1)
    logits = forward(params, cfg, full, mask, use_flash=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    S = prompt.shape[1]
    # logits at position j predict token j+1
    pred = logp[:, S - 1:-1, :]
    tgt = cont.astype(jnp.int32)
    return np.asarray(jnp.take_along_axis(
        pred, tgt[:, :, None], axis=-1)[..., 0].sum(axis=1))


def test_beam1_matches_greedy():
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens, mask = _data()
    out_g, len_g = jax.jit(lambda p, t, m: greedy_generate(
        p, CFG, t, m, 8))(params, tokens, mask)
    out_b, len_b = jax.jit(lambda p, t, m: beam_generate(
        p, CFG, t, m, 8, num_beams=1))(params, tokens, mask)
    np.testing.assert_array_equal(np.asarray(out_g), np.asarray(out_b))
    np.testing.assert_array_equal(np.asarray(len_g), np.asarray(len_b))


def test_beam_score_at_least_greedy():
    params = init_params(CFG, jax.random.PRNGKey(1))
    tokens, mask = _data(B=4, seed=5)
    T = 6
    out_g, _ = jax.jit(lambda p, t, m: greedy_generate(
        p, CFG, t, m, T))(params, tokens, mask)
    out_b, _ = jax.jit(lambda p, t, m: beam_generate(
        p, CFG, t, m, T, num_beams=4))(params, tokens, mask)
    sg = _seq_score(params, CFG, tokens, mask, out_g)
    sb = _seq_score(params, CFG, tokens, mask, out_b)
    assert (sb >= sg - 1e-4).all(), (sb, sg)


def test_beam_finds_exhaustive_best_tiny_vocab():
    """With num_beams >= vocab^1 the first expansion is exhaustive and a
    2-step search over a tiny vocab must find the global best 2-token
    continuation (verified by brute force over all vocab^2 sequences)."""
    cfg = dataclasses.replace(CFG, vocab_size=8)
    params = init_params(cfg, jax.random.PRNGKey(2))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (1, 6), 0, 8)
    mask = jnp.ones((1, 6), bool)
    T = 2
    out_b, _ = jax.jit(lambda p, t, m: beam_generate(
        p, cfg, t, m, T, num_beams=8))(params, tokens, mask)
    # brute force: score all 64 continuations with the parallel forward
    cand = jnp.asarray([[a, b] for a in range(8) for b in range(8)],
                       jnp.int32)
    scores = _seq_score(params, cfg, jnp.repeat(tokens, 64, 0),
                        jnp.repeat(mask, 64, 0), cand)
    got = _seq_score(params, cfg, tokens, mask,
                     jnp.asarray(out_b, jnp.int32))
    assert float(got[0]) >= float(scores.max()) - 1e-4, \
        (np.asarray(out_b), float(got[0]), float(scores.max()))


def test_beam_eos_freezes_and_lengths():
    """Force EOS to be the most likely token everywhere by biasing the
    output head: beams should finish immediately with length 1 and pad
    the rest."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    eos = 5
    # output contract on a normal model: everything after first EOS pad
    out, lengths = jax.jit(lambda p, t, m: beam_generate(
        p, CFG, t, m, 10, num_beams=3, eos_token_id=eos,
        pad_token_id=0))(params, *_data(B=3, seed=11))
    out, lengths = np.asarray(out), np.asarray(lengths)
    for i in range(out.shape[0]):
        row = out[i]
        if (row == eos).any():
            first = int(np.argmax(row == eos))
            assert lengths[i] == first + 1
            assert (row[first + 1:] == 0).all()
        else:
            assert lengths[i] == 10


def test_beam_length_penalty_prefers_longer():
    """length_penalty > 1 divides by a larger factor for longer beams —
    the selection must honor the normalized (not raw) score ordering.
    Indirect check: selection with an extreme penalty still returns a
    valid beam and runs under jit."""
    params = init_params(CFG, jax.random.PRNGKey(4))
    tokens, mask = _data(B=2, seed=9)
    out_a, _ = jax.jit(lambda p, t, m: beam_generate(
        p, CFG, t, m, 6, num_beams=3, eos_token_id=1,
        length_penalty=0.2))(params, tokens, mask)
    out_b, _ = jax.jit(lambda p, t, m: beam_generate(
        p, CFG, t, m, 6, num_beams=3, eos_token_id=1,
        length_penalty=3.0))(params, tokens, mask)
    assert out_a.shape == out_b.shape == (2, 6)


def test_jaxlm_num_beams_plumbing():
    lm = JaxLM(config='tiny', max_seq_len=128,
               generation_kwargs={'num_beams': 3})
    out = lm.generate(['hello world test'], max_out_len=5)
    assert len(out) == 1 and isinstance(out[0], str)


def test_beam_with_quant_and_kv4_runs():
    """The headline decode config (W8A8 + int4 KV) composes with beam
    search (cache tiling + gather must preserve the quantized cache's
    scale leaves)."""
    from opencompass_tpu.nn.quant import quantize_params
    cfgq = dataclasses.replace(CFG, act_quant=True, kv_quant='int4')
    params = quantize_params(init_params(CFG, jax.random.PRNGKey(0)), CFG)
    tokens, mask = _data()
    out, lengths = jax.jit(lambda p, t, m: beam_generate(
        p, cfgq, t, m, 6, num_beams=3))(params, tokens, mask)
    assert out.shape == (2, 6)
    assert np.asarray(out).max() < CFG.vocab_size
