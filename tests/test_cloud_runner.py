"""CloudRunner: submit-template wrapping, retry-on-missing-output."""
import os.path as osp

import pytest

from opencompass_tpu.config import Config
from opencompass_tpu.partitioners import NaivePartitioner
from opencompass_tpu.runners import CloudRunner

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


def _tasks(tmp_path):
    cfg = Config.fromfile(osp.join(REPO, 'configs/eval_demo.py'))
    cfg['work_dir'] = str(tmp_path)
    cfg['datasets'] = cfg['datasets'][:1]  # one (model, dataset) task
    return NaivePartitioner(str(tmp_path / 'predictions'))(cfg)


def test_cloud_runner_runs_through_fake_submit(tmp_path):
    tasks = _tasks(tmp_path)
    marker = str(tmp_path / 'submitted.txt')
    runner = CloudRunner(
        task=dict(type='OpenICLInferTask'),
        submit_template=('echo name={name} devices={num_devices} >> '
                         f'{marker} && {{task_cmd}}'),
        submit_jitter=0.0, retry=0)
    status = runner.launch(tasks)
    assert status[0][1] == 0, status
    # the fake cloud CLI saw the wrapped submission with fields filled
    submitted = open(marker).read()
    assert 'name=OpenICLInfer_fake-demo_demo-gen' in submitted
    assert 'devices=0' in submitted
    # the task really ran: outputs exist
    work = str(tmp_path)
    assert osp.exists(osp.join(work, 'predictions', 'fake-demo',
                               'demo-gen.json'))


def test_cloud_runner_retries_until_outputs_exist(tmp_path):
    tasks = _tasks(tmp_path)
    attempts = str(tmp_path / 'attempts')
    # first submission "succeeds" (rc 0) but produces no outputs —
    # preemption-shaped failure; second runs the real task
    flaky = (f'echo x >> {attempts}; '
             f'if [ $(wc -l < {attempts}) -ge 2 ]; then {{task_cmd}}; '
             f'else true; fi')
    runner = CloudRunner(task=dict(type='OpenICLInferTask'),
                         submit_template=flaky, submit_jitter=0.0, retry=2)
    status = runner.launch(tasks)
    assert status[0][1] == 0
    assert open(attempts).read().count('x') == 2
    assert osp.exists(osp.join(str(tmp_path), 'predictions', 'fake-demo',
                               'demo-gen.json'))


def test_cloud_runner_fails_after_retry_budget(tmp_path):
    tasks = _tasks(tmp_path)
    runner = CloudRunner(task=dict(type='OpenICLInferTask'),
                         submit_template='true || {task_cmd}',
                         submit_jitter=0.0, retry=1)
    status = runner.launch(tasks)
    assert status[0][1] != 0  # rc 0 but outputs never appear → failure


def test_cloud_runner_requires_task_cmd_placeholder():
    with pytest.raises(ValueError, match='task_cmd'):
        CloudRunner(task=dict(type='OpenICLInferTask'),
                    submit_template='gcloud submit')
