"""CloudRunner: submit-template wrapping, retry-on-missing-output."""
import os.path as osp

import pytest

from opencompass_tpu.config import Config
from opencompass_tpu.partitioners import NaivePartitioner
from opencompass_tpu.runners import CloudRunner

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


def _tasks(tmp_path):
    cfg = Config.fromfile(osp.join(REPO, 'configs/eval_demo.py'))
    cfg['work_dir'] = str(tmp_path)
    cfg['datasets'] = cfg['datasets'][:1]  # one (model, dataset) task
    return NaivePartitioner(str(tmp_path / 'predictions'))(cfg)


def test_cloud_runner_runs_through_fake_submit(tmp_path):
    tasks = _tasks(tmp_path)
    marker = str(tmp_path / 'submitted.txt')
    runner = CloudRunner(
        task=dict(type='OpenICLInferTask'),
        submit_template=('echo name={name} devices={num_devices} >> '
                         f'{marker} && {{task_cmd}}'),
        submit_jitter=0.0, retry=0)
    status = runner.launch(tasks)
    assert status[0][1] == 0, status
    # the fake cloud CLI saw the wrapped submission with fields filled
    submitted = open(marker).read()
    assert 'name=OpenICLInfer_fake-demo_demo-gen' in submitted
    assert 'devices=0' in submitted
    # the task really ran: outputs exist
    work = str(tmp_path)
    assert osp.exists(osp.join(work, 'predictions', 'fake-demo',
                               'demo-gen.json'))


def test_cloud_runner_retries_until_outputs_exist(tmp_path):
    tasks = _tasks(tmp_path)
    attempts = str(tmp_path / 'attempts')
    # first submission "succeeds" (rc 0) but produces no outputs —
    # preemption-shaped failure; second runs the real task
    flaky = (f'echo x >> {attempts}; '
             f'if [ $(wc -l < {attempts}) -ge 2 ]; then {{task_cmd}}; '
             f'else true; fi')
    runner = CloudRunner(task=dict(type='OpenICLInferTask'),
                         submit_template=flaky, submit_jitter=0.0, retry=2)
    status = runner.launch(tasks)
    assert status[0][1] == 0
    assert open(attempts).read().count('x') == 2
    assert osp.exists(osp.join(str(tmp_path), 'predictions', 'fake-demo',
                               'demo-gen.json'))


def test_cloud_runner_fails_after_retry_budget(tmp_path):
    tasks = _tasks(tmp_path)
    runner = CloudRunner(task=dict(type='OpenICLInferTask'),
                         submit_template='true || {task_cmd}',
                         submit_jitter=0.0, retry=1)
    status = runner.launch(tasks)
    assert status[0][1] != 0  # rc 0 but outputs never appear → failure


def test_cloud_runner_requires_task_cmd_placeholder():
    with pytest.raises(ValueError, match='task_cmd'):
        CloudRunner(task=dict(type='OpenICLInferTask'),
                    submit_template='gcloud submit')


def test_dlc_submit_line_quotes_config_values(monkeypatch, tmp_path):
    """Paths with spaces/quotes in aliyun_cfg (or the cwd) must not split
    the submit line: the whole inner command is shlex-quoted once and the
    flag values individually."""
    import shlex
    from opencompass_tpu.runners.dlc import DLCRunner
    weird = tmp_path / 'my dir'
    weird.mkdir()
    monkeypatch.chdir(weird)
    runner = DLCRunner(
        dict(type='OpenICLInferTask'),
        aliyun_cfg=dict(bashrc_path='/home/my user/.bashrc',
                        conda_env_name="eval's env",
                        worker_image='repo/image:v1',
                        workspace_id='ws 42'))
    line = runner.submit_template
    # the submit host's shell tokenizes the line cleanly...
    final = line.replace('{task_cmd}', 'python -m opencompass_tpu.tasks c.py') \
                .replace('{name}', 'n').replace('{num_devices}', '1')
    toks = shlex.split(final)
    assert toks[:3] == ['dlc', 'create', 'job']
    assert toks[toks.index('--workspace_id') + 1] == 'ws 42'
    # ...and the WORKER's shell re-parses the inner command, so each
    # setup statement must tokenize back to intact values there too
    cmd = toks[toks.index('--command') + 1]
    stmts = [shlex.split(s.strip()) for s in cmd.split(';')]
    assert stmts[0] == ['source', '/home/my user/.bashrc']
    assert stmts[1] == ['conda', 'activate', "eval's env"]
    assert stmts[2] == ['cd', str(weird)]
    assert stmts[3] == ['python', '-m', 'opencompass_tpu.tasks', 'c.py']
