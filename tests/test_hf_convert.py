"""HF checkpoint conversion: name mapping, transposes, fused-QKV splits."""
import json
import os

import numpy as np
import pytest

from opencompass_tpu.nn.config import TransformerConfig
from opencompass_tpu.nn.hf_convert import convert_checkpoint


def _write_ckpt(tmpdir, hf_config, tensors):
    with open(os.path.join(tmpdir, 'config.json'), 'w') as f:
        json.dump(hf_config, f)
    from safetensors.numpy import save_file
    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()},
              os.path.join(tmpdir, 'model.safetensors'))


def test_llama_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    D, F, V, L, H = 16, 32, 64, 2, 4
    hf = dict(model_type='llama', vocab_size=V, hidden_size=D,
              num_hidden_layers=L, num_attention_heads=H,
              num_key_value_heads=2, intermediate_size=F,
              max_position_embeddings=128, rms_norm_eps=1e-6,
              tie_word_embeddings=False)
    hd = D // H
    kv = 2 * hd
    tensors = {'model.embed_tokens.weight': rng.randn(V, D),
               'model.norm.weight': np.ones(D),
               'lm_head.weight': rng.randn(V, D)}
    for i in range(L):
        p = f'model.layers.{i}'
        tensors[f'{p}.input_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.post_attention_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.self_attn.q_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.self_attn.k_proj.weight'] = rng.randn(kv, D)
        tensors[f'{p}.self_attn.v_proj.weight'] = rng.randn(kv, D)
        tensors[f'{p}.self_attn.o_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.mlp.gate_proj.weight'] = rng.randn(F, D)
        tensors[f'{p}.mlp.up_proj.weight'] = rng.randn(F, D)
        tensors[f'{p}.mlp.down_proj.weight'] = rng.randn(D, F)
    tensors = {k: v.astype(np.float32) for k, v in tensors.items()}
    _write_ckpt(str(tmp_path), hf, tensors)

    cfg, params = convert_checkpoint(str(tmp_path))
    assert cfg.num_kv_heads == 2
    assert params['embed'].shape == (V, D)
    assert params['lm_head'].shape == (D, V)  # transposed
    # q/k/v keep torch's (out, in) orientation (transformer._linear_nt)
    np.testing.assert_allclose(
        np.asarray(params['layers']['q']['w'][0], np.float32),
        tensors['model.layers.0.self_attn.q_proj.weight'], rtol=1e-2)
    assert params['layers']['k']['w'].shape == (L, kv, D)

    # converted params must run through the model
    import jax.numpy as jnp
    from opencompass_tpu.nn import forward
    jp = {k: v for k, v in params.items()}
    toks = jnp.arange(8)[None, :] % V
    logits = forward(jax.tree_util.tree_map(jnp.asarray, jp), cfg, toks)
    assert logits.shape == (1, 8, V)


import jax  # noqa: E402  (used above after conversion)


def test_gpt2_fused_qkv_split(tmp_path):
    rng = np.random.RandomState(1)
    D, V, L, H = 8, 32, 1, 2
    hf = dict(model_type='gpt2', vocab_size=V, n_embd=D, n_layer=L,
              n_head=H, n_inner=None, n_positions=64)
    tensors = {
        'wte.weight': rng.randn(V, D), 'wpe.weight': rng.randn(64, D),
        'ln_f.weight': np.ones(D), 'ln_f.bias': np.zeros(D),
        'h.0.ln_1.weight': np.ones(D), 'h.0.ln_1.bias': np.zeros(D),
        'h.0.ln_2.weight': np.ones(D), 'h.0.ln_2.bias': np.zeros(D),
        'h.0.attn.c_attn.weight': rng.randn(D, 3 * D),  # Conv1D: (in, out)
        'h.0.attn.c_attn.bias': rng.randn(3 * D),
        'h.0.attn.c_proj.weight': rng.randn(D, D),
        'h.0.attn.c_proj.bias': rng.randn(D),
        'h.0.mlp.c_fc.weight': rng.randn(D, 4 * D),
        'h.0.mlp.c_fc.bias': rng.randn(4 * D),
        'h.0.mlp.c_proj.weight': rng.randn(4 * D, D),
        'h.0.mlp.c_proj.bias': rng.randn(D),
    }
    tensors = {k: v.astype(np.float32) for k, v in tensors.items()}
    _write_ckpt(str(tmp_path), hf, tensors)
    cfg, params = convert_checkpoint(str(tmp_path))
    fused = tensors['h.0.attn.c_attn.weight']
    np.testing.assert_allclose(
        np.asarray(params['layers']['q']['w'][0], np.float32),
        fused[:, :D].T, rtol=1e-2)
    np.testing.assert_allclose(
        np.asarray(params['layers']['v']['w'][0], np.float32),
        fused[:, 2 * D:].T, rtol=1e-2)
    assert 'lm_head' not in params  # tied


def test_falcon_mqa_split(tmp_path):
    rng = np.random.RandomState(2)
    D, V, L, H, hd = 8, 32, 1, 4, 2
    hf = dict(model_type='falcon', vocab_size=V, hidden_size=D,
              num_hidden_layers=L, num_attention_heads=H, num_kv_heads=1)
    fused = rng.randn((H + 2) * hd, D).astype(np.float32)
    tensors = {
        'transformer.word_embeddings.weight':
            rng.randn(V, D).astype(np.float32),
        'transformer.ln_f.weight': np.ones(D, np.float32),
        'transformer.ln_f.bias': np.zeros(D, np.float32),
        'transformer.h.0.input_layernorm.weight': np.ones(D, np.float32),
        'transformer.h.0.input_layernorm.bias': np.zeros(D, np.float32),
        'transformer.h.0.self_attention.query_key_value.weight': fused,
        'transformer.h.0.self_attention.dense.weight':
            rng.randn(D, D).astype(np.float32),
        'transformer.h.0.mlp.dense_h_to_4h.weight':
            rng.randn(4 * D, D).astype(np.float32),
        'transformer.h.0.mlp.dense_4h_to_h.weight':
            rng.randn(D, 4 * D).astype(np.float32),
    }
    _write_ckpt(str(tmp_path), hf, tensors)
    cfg, params = convert_checkpoint(str(tmp_path))
    assert params['layers']['q']['w'].shape == (L, H * hd, D)
    assert params['layers']['k']['w'].shape == (L, hd, D)
    np.testing.assert_allclose(
        np.asarray(params['layers']['k']['w'][0], np.float32),
        fused[H * hd:(H + 1) * hd, :], rtol=1e-2)


def test_unknown_family_raises(tmp_path):
    with open(os.path.join(str(tmp_path), 'config.json'), 'w') as f:
        json.dump(dict(model_type='mamba'), f)
    with pytest.raises(ValueError, match='unsupported|no weight map'):
        cfg = TransformerConfig.tiny()
        convert_checkpoint(str(tmp_path), cfg)


def test_convert_cache_roundtrip(tmp_path, monkeypatch):
    from opencompass_tpu.nn import hf_convert
    rng = np.random.RandomState(1)
    D, F, V, L, H = 16, 32, 64, 2, 4
    hf = dict(model_type='llama', vocab_size=V, hidden_size=D,
              num_hidden_layers=L, num_attention_heads=H,
              num_key_value_heads=2, intermediate_size=F,
              max_position_embeddings=128, rms_norm_eps=1e-6,
              tie_word_embeddings=False)
    hd = D // H
    kv = 2 * hd
    tensors = {'model.embed_tokens.weight': rng.randn(V, D),
               'model.norm.weight': np.ones(D),
               'lm_head.weight': rng.randn(V, D)}
    for i in range(L):
        p = f'model.layers.{i}'
        tensors[f'{p}.input_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.post_attention_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.self_attn.q_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.self_attn.k_proj.weight'] = rng.randn(kv, D)
        tensors[f'{p}.self_attn.v_proj.weight'] = rng.randn(kv, D)
        tensors[f'{p}.self_attn.o_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.mlp.gate_proj.weight'] = rng.randn(F, D)
        tensors[f'{p}.mlp.up_proj.weight'] = rng.randn(F, D)
        tensors[f'{p}.mlp.down_proj.weight'] = rng.randn(D, F)
    tensors = {k: v.astype(np.float32) for k, v in tensors.items()}
    ckpt = tmp_path / 'ckpt'
    ckpt.mkdir()
    _write_ckpt(str(ckpt), hf, tensors)
    cache = tmp_path / 'cache'

    cfg1, p1 = hf_convert.convert_checkpoint_cached(
        str(ckpt), cache_dir=str(cache))
    # second load must come from cache — make a re-conversion impossible
    monkeypatch.setattr(hf_convert, '_iter_checkpoint_tensors',
                        lambda *_: (_ for _ in ()).throw(
                            AssertionError('re-converted instead of '
                                           'using cache')))
    cfg2, p2 = hf_convert.convert_checkpoint_cached(
        str(ckpt), cache_dir=str(cache))
    assert cfg2 == cfg1
    flat1 = hf_convert._flatten_tree(p1)
    flat2 = hf_convert._flatten_tree(p2)
    assert set(flat1) == set(flat2)
    for k in flat1:
        a, b = np.asarray(flat1[k]), np.asarray(flat2[k])
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(
            a.view(np.uint8).ravel(), b.view(np.uint8).ravel())

    # a requested cfg wins over the cached manifest on hits...
    import dataclasses
    req = dataclasses.replace(cfg1, kv_quant=True)
    cfg3, _ = hf_convert.convert_checkpoint_cached(
        str(ckpt), cfg=req, cache_dir=str(cache))
    assert cfg3.kv_quant
    # ...and runtime flags never leak INTO the stored manifest
    cfg4, _ = hf_convert.convert_checkpoint_cached(
        str(ckpt), cfg=None, cache_dir=str(cache))
    assert not cfg4.kv_quant


def test_convert_cache_corrupt_manifest_falls_back(tmp_path, monkeypatch):
    from opencompass_tpu.nn import hf_convert
    rng = np.random.RandomState(2)
    D, V = 16, 64
    hf = dict(model_type='llama', vocab_size=V, hidden_size=D,
              num_hidden_layers=1, num_attention_heads=4,
              num_key_value_heads=2, intermediate_size=32,
              max_position_embeddings=128, rms_norm_eps=1e-6,
              tie_word_embeddings=False)
    hd = D // 4
    tensors = {'model.embed_tokens.weight': rng.randn(V, D),
               'model.norm.weight': np.ones(D),
               'lm_head.weight': rng.randn(V, D),
               'model.layers.0.input_layernorm.weight': np.ones(D),
               'model.layers.0.post_attention_layernorm.weight': np.ones(D),
               'model.layers.0.self_attn.q_proj.weight': rng.randn(D, D),
               'model.layers.0.self_attn.k_proj.weight':
                   rng.randn(2 * hd, D),
               'model.layers.0.self_attn.v_proj.weight':
                   rng.randn(2 * hd, D),
               'model.layers.0.self_attn.o_proj.weight': rng.randn(D, D),
               'model.layers.0.mlp.gate_proj.weight': rng.randn(32, D),
               'model.layers.0.mlp.up_proj.weight': rng.randn(32, D),
               'model.layers.0.mlp.down_proj.weight': rng.randn(D, 32)}
    tensors = {k: v.astype(np.float32) for k, v in tensors.items()}
    ckpt = tmp_path / 'ckpt'
    ckpt.mkdir()
    _write_ckpt(str(ckpt), hf, tensors)
    cache = tmp_path / 'cache'
    cfg1, _ = hf_convert.convert_checkpoint_cached(str(ckpt),
                                                   cache_dir=str(cache))
    # truncate the manifest: a later load must re-convert, not crash
    loc = next(cache.iterdir())
    (loc / 'manifest.json').write_text('{"config": {')
    cfg2, p2 = hf_convert.convert_checkpoint_cached(str(ckpt),
                                                    cache_dir=str(cache))
    assert cfg2 == cfg1 and 'embed' in p2


def test_convert_cache_keys_on_structural_cfg(tmp_path):
    """A truncated/overridden cfg must not collide with the full-model
    entry (different stored pytrees)."""
    import dataclasses
    from opencompass_tpu.nn import hf_convert
    rng = np.random.RandomState(3)
    D, V = 16, 64
    hf = dict(model_type='llama', vocab_size=V, hidden_size=D,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, intermediate_size=32,
              max_position_embeddings=128, rms_norm_eps=1e-6,
              tie_word_embeddings=False)
    hd = D // 4
    tensors = {'model.embed_tokens.weight': rng.randn(V, D),
               'model.norm.weight': np.ones(D),
               'lm_head.weight': rng.randn(V, D)}
    for i in range(2):
        p = f'model.layers.{i}'
        tensors[f'{p}.input_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.post_attention_layernorm.weight'] = np.ones(D)
        tensors[f'{p}.self_attn.q_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.self_attn.k_proj.weight'] = rng.randn(2 * hd, D)
        tensors[f'{p}.self_attn.v_proj.weight'] = rng.randn(2 * hd, D)
        tensors[f'{p}.self_attn.o_proj.weight'] = rng.randn(D, D)
        tensors[f'{p}.mlp.gate_proj.weight'] = rng.randn(32, D)
        tensors[f'{p}.mlp.up_proj.weight'] = rng.randn(32, D)
        tensors[f'{p}.mlp.down_proj.weight'] = rng.randn(D, 32)
    tensors = {k: v.astype(np.float32) for k, v in tensors.items()}
    ckpt = tmp_path / 'ckpt'
    ckpt.mkdir()
    _write_ckpt(str(ckpt), hf, tensors)

    full = hf_convert.TransformerConfig.from_hf_config(
        hf_convert.load_hf_config(str(ckpt)))
    trunc = dataclasses.replace(full, num_layers=1)
    k_none = hf_convert._ckpt_fingerprint(str(ckpt), None)
    k_full = hf_convert._ckpt_fingerprint(str(ckpt), full)
    k_trunc = hf_convert._ckpt_fingerprint(str(ckpt), trunc)
    assert k_none == k_full            # derived == explicit-equivalent
    assert k_trunc != k_full           # structural change = new entry
    # runtime flags don't fork entries
    k_kv = hf_convert._ckpt_fingerprint(
        str(ckpt), dataclasses.replace(full, kv_quant=True))
    assert k_kv == k_full
