"""OCT007 firing: per-call jit wrappers and unhashable static args."""
import jax

scored = jax.jit(lambda p, t: p @ t, static_argnums=1)


def score_once(params, tokens):
    # fresh wrapper (fresh compile cache) every call: OCT007
    return jax.jit(lambda p: p @ tokens)(params)


def score_all(params, batches):
    out = []
    for batch in batches:
        out.append(jax.jit(lambda p: p @ batch)(params))   # OCT007
    return out


def score_shapes(params):
    # list literal in a static position is unhashable: OCT007
    return scored(params, [4, 128])
