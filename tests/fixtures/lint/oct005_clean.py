"""OCT005 clean: the injected-clock fallback shape."""
# oct-lint: clock-discipline
import time


def queue_age(submitted_ts, now=None):
    now = time.time() if now is None else now
    return now - submitted_ts
