"""OCT001 firing: JSONL append through bare open()/os.open."""
import json
import os


def log_event(path, rec):
    with open(path, 'a') as f:          # torn-line hazard: OCT001
        f.write(json.dumps(rec) + '\n')


def raw_append(path, data):
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND)  # OCT001
    os.write(fd, data)
    os.close(fd)
