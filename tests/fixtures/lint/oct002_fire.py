"""OCT002 firing: state file written non-atomically."""
import json


def save_state(path, state):
    with open(path, 'w') as f:
        json.dump(state, f)          # reader can see half a file: OCT002
