"""OCT004 firing: fire-and-forget non-daemon thread."""
import threading


def start_background(fn):
    threading.Thread(target=fn).start()      # never joined: OCT004


def start_named(fn):
    t = threading.Thread(target=fn, name='worker')   # OCT004
    t.start()
    return t
