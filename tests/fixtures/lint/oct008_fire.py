"""OCT008 firing: hand-rolled torn-tail seal via a seek(-1, ...) probe."""
import os


def seal_tail(path):
    with open(path, 'rb+') as f:
        f.seek(0, os.SEEK_END)
        if f.tell() == 0:
            return
        f.seek(-1, os.SEEK_END)         # tail-byte probe: OCT008
        if f.read(1) != b'\n':
            f.write(b'\n')
