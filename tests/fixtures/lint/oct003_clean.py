"""OCT003 clean: every guarded access under the lock, or in a
``*_locked`` caller-holds helper."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._slots = []
        # guarded-by: _lock
        self._queue = []
        self._queue.append(0)            # __init__ is single-threaded

    def submit(self, row):
        with self._lock:
            self._queue.append(row)

    def occupancy(self):
        with self._lock:
            return len(self._slots) + self._peek_locked()

    def _peek_locked(self):
        return len(self._queue)          # caller holds _lock
