"""OCT008 clean: the shared helper owns the torn-tail discipline."""
from opencompass_tpu.utils.journal import journal_append, seal_torn_tail


def log_event(path, line):
    journal_append(path, line)


def recover(path):
    seal_torn_tail(path)


def read_back(path):
    with open(path, 'rb') as f:
        f.seek(0, 2)                    # absolute/positive seeks: fine
        size = f.tell()
        f.seek(max(size - 4096, 0))
        return f.read()
