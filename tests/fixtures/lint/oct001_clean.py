"""OCT001 clean: appends ride the single-write helper; reads are fine."""
from opencompass_tpu.utils.fileio import append_jsonl_atomic


def log_event(path, rec):
    append_jsonl_atomic(path, [rec])


def read_back(path):
    with open(path, encoding='utf-8') as f:   # read mode: not flagged
        return f.read()
