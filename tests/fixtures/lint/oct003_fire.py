"""OCT003 firing: guarded attribute touched without its lock."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._slots = []
        # guarded-by: _lock
        self._queue = []

    def submit(self, row):
        self._queue.append(row)          # no lock held: OCT003

    def occupancy(self):
        with self._lock:
            return len(self._slots) + self.peek()

    def peek(self):
        return len(self._queue)          # lexically lock-free: OCT003
