"""OCT005 firing: bare wall-clock read in a clock-disciplined module."""
# oct-lint: clock-discipline
import time


def queue_age(submitted_ts):
    return time.time() - submitted_ts        # not injectable: OCT005
