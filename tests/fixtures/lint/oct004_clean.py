"""OCT004 clean: daemonized, or joined before return."""
import threading


def start_background(fn):
    threading.Thread(target=fn, daemon=True).start()


def run_to_completion(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
