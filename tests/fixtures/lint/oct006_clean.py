"""OCT006 clean: the compiled function stays on device; host
transfers happen at the call site."""
import jax
import numpy as np


def step(params, tokens):
    logits = params @ tokens
    return logits


step_fn = jax.jit(step)


def drive(params, tokens):
    logits = step_fn(params, tokens)
    return np.asarray(logits)       # sync outside the jitted body: fine
