"""OCT007 clean: one wrapper, hoisted; statics are hashable."""
import jax


def _score(p, t, shape):
    return (p @ t).reshape(shape)


score_fn = jax.jit(_score, static_argnums=2)

# immediate invocation at module import runs exactly once: fine
_warm = jax.jit(lambda x: x + 1)


def score_all(params, batches):
    return [score_fn(params, b, (4, 128)) for b in batches]
