"""OCT006 firing: host sync inside a jitted step function."""
import jax
import numpy as np


def step(params, tokens):
    logits = params @ tokens
    peak = float(np.asarray(logits).max())   # device→host sync: OCT006
    return logits * peak


step_fn = jax.jit(step)


@jax.jit
def decode_step(cache, tok):
    out = cache + tok
    return out, out.item()                   # sync per step: OCT006
