"""OCT002 clean: atomic helper, or an explicit temp + os.replace."""
import json
import os


def save_state(path, state):
    from opencompass_tpu.utils.fileio import atomic_write_json
    atomic_write_json(path, state)


def save_state_by_hand(path, state):
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(state, f)          # dump target is the temp file
    os.replace(tmp, path)            # ...and the replace commits it
