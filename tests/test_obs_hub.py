"""Fleet observability hub: durable multi-source aggregation,
tail-based trace sampling, metric rollups with retention, cross-run
regression attribution — plus the satellites that ride with it (the
shared journal helper + OCT008, promexport staleness, the doctor
disk-pressure rule, the chaos deadline-skew knob, and the hub
crash-fuzz contract)."""
import json
import os
import os.path as osp

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


def _write_jsonl(path, records):
    os.makedirs(osp.dirname(path), exist_ok=True)
    with open(path, 'a', encoding='utf-8') as f:  # oct-lint: disable=OCT001(test fixture writer, single process)
        for rec in records:
            f.write(json.dumps(rec) + '\n')


def _mk_requests(n, t0, model='tiny', err_every=25, wall=None):
    recs = []
    for i in range(n):
        w = wall(i) if wall else 0.05 + (i % 20) * 0.01
        recs.append({
            'v': 1, 'id': f'req-{model}-{i}', 'ts': round(t0 + i * 0.5, 3),
            'route': '/v1/completions', 'model': model,
            'status': 'error' if i % err_every == 7 else 'ok',
            'wall_s': round(w, 5),
            'phases': [{'name': 'prefill', 'start_s': 0.0,
                        'dur_s': round(w * 0.4, 5)},
                       {'name': 'decode', 'start_s': round(w * 0.4, 5),
                        'dur_s': round(w * 0.6, 5)}],
        })
    return recs


@pytest.fixture
def obs_run(tmp_path):
    """One synthetic source obs dir: 200 completions (8 errors), one
    SLO burn interval covering ts [t0+30, t0+40]."""
    from opencompass_tpu.obs import hub as hubmod
    root = str(tmp_path / 'fleet')
    src = osp.join(root, 'w0', 'obs')
    t0 = 1_700_000_000.0
    recs = _mk_requests(200, t0)
    _write_jsonl(osp.join(src, 'requests.jsonl'), recs)
    _write_jsonl(osp.join(src, 'alerts.jsonl'), [
        {'t': 'fire', 'rule': 'completion_p99', 'ts': t0 + 30.0},
        {'t': 'resolve', 'rule': 'completion_p99', 'ts': t0 + 40.0}])
    hubmod.register_source(root, 'hostA', 'worker', src)
    return {'root': root, 'src': src, 't0': t0, 'recs': recs,
            'now': t0 + 200 * 0.5 + 30.0}


# -- utils/journal.py (satellite 1) -----------------------------------------

def test_journal_roundtrip_and_torn_tail(tmp_path):
    from opencompass_tpu.utils.journal import (journal_append,
                                               read_journal,
                                               seal_torn_tail)
    path = str(tmp_path / 'j.jsonl')
    journal_append(path, [{'a': 1}, {'a': 2}], version=1)
    assert [r['a'] for r in read_journal(path)] == [1, 2]
    # a dead writer's torn final line is sealed, not fatal
    with open(path, 'ab') as f:  # oct-lint: disable=OCT001(test: simulating a torn write)
        f.write(b'{"a": 3')
    seal_torn_tail(path)
    journal_append(path, [{'a': 4}], version=1)
    assert [r.get('a') for r in read_journal(path)
            if 'a' in r] == [1, 2, 4]


def test_journal_reads_segments_first(tmp_path):
    from opencompass_tpu.utils.journal import journal_append, read_journal
    path = str(tmp_path / 'j.jsonl')
    journal_append(path + '.1', [{'a': 'old'}], version=1)
    journal_append(path, [{'a': 'new'}], version=1)
    assert [r['a'] for r in read_journal(path)] == ['old', 'new']


def test_oct008_flags_tail_probe(tmp_path):
    from opencompass_tpu.analysis.linter import run_lint
    src = tmp_path / 'mod.py'
    src.write_text(
        "import os\n"
        "def probe(f):\n"
        "    f.seek(-1, os.SEEK_END)\n"
        "    return f.read(1)\n")
    report = run_lint([str(src)], baseline_path=None)
    assert 'OCT008' in {f.rule for f in report.active}


def test_oct008_journal_module_exempt():
    from opencompass_tpu.analysis.linter import run_lint
    path = osp.join(REPO, 'opencompass_tpu', 'utils', 'journal.py')
    report = run_lint([path], baseline_path=None)
    assert 'OCT008' not in {f.rule for f in report.active}


# -- tail-based sampling ----------------------------------------------------

def test_tail_sampling_keeps_all_errors_and_burn(obs_run):
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(obs_run['root'], rate=0.0)
    stats = hub.ingest(now=obs_run['now'], force_flush=True)
    assert stats['ingested'] >= 200
    traces = {t['trace']: t for t in hub.read_traces()}
    # 100% of error traces survive a zero sample rate
    error_ids = {r['id'] for r in obs_run['recs']
                 if r['status'] == 'error'}
    assert error_ids <= set(traces)
    assert all(traces[i]['keep'] == 'error' for i in error_ids)
    # completions inside the fire..resolve burn interval survive too
    t0 = obs_run['t0']
    burn_ids = {r['id'] for r in obs_run['recs']
                if t0 + 30.0 <= r['ts'] <= t0 + 40.0
                and r['status'] == 'ok'}
    assert burn_ids and burn_ids <= set(traces)
    assert {traces[i]['keep'] for i in burn_ids} <= {'slo_burn',
                                                     'p99_slow'}
    # the healthy bulk was NOT all kept, but every completion counted
    assert len(traces) < 200
    ans = hub.query(since=t0 - 1, until=obs_run['now'], q=0.5,
                    now=obs_run['now'])
    assert ans['count'] == 200 and ans['errors'] == len(error_ids)


def test_hash_sampling_is_deterministic(tmp_path):
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(str(tmp_path), rate=0.3)
    picks = [hub._hash_sampled(f'trace-{i}') for i in range(500)]
    assert picks == [hub._hash_sampled(f'trace-{i}') for i in range(500)]
    assert 0.15 < sum(picks) / len(picks) < 0.45


def test_degraded_and_slow_keep_reasons(tmp_path):
    from opencompass_tpu.obs import hub as hubmod
    src = str(tmp_path / 'obs')
    t0 = 1_700_000_000.0
    recs = _mk_requests(100, t0, err_every=10 ** 9)
    recs[50]['degraded'] = True
    recs[99]['wall_s'] = 9.5    # far past the rolling p99
    _write_jsonl(osp.join(src, 'requests.jsonl'), recs)
    hub = hubmod.ObsHub(src, rate=0.0)
    hub.ingest(now=t0 + 120.0, force_flush=True)
    traces = {t['trace']: t['keep'] for t in hub.read_traces()}
    assert traces.get('req-tiny-50') == 'degraded'
    assert traces.get('req-tiny-99') == 'p99_slow'


# -- rollups: the acceptance bar --------------------------------------------

def test_rollup_p99_matches_raw_after_raw_deleted(obs_run):
    """`cli obs query` must answer p99 from rollups alone, within 5%
    of the raw-stream answer, after the raw streams are gone."""
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(obs_run['root'], budget_bytes=1)
    hub.ingest(now=obs_run['now'], force_flush=True)
    since, until = obs_run['t0'] - 1, obs_run['now']
    raw = hub.query(since=since, until=until, q=0.99, raw=True,
                    now=until)
    assert raw['count'] == 200 and raw['value_s'] is not None
    hub.compact(now=until)
    assert not osp.isfile(osp.join(obs_run['src'], 'requests.jsonl'))
    ans = hubmod.ObsHub(obs_run['root'], budget_bytes=1).query(
        since=since, until=until, q=0.99, now=until)
    assert ans['source'] == 'rollups' and ans['count'] == 200
    assert abs(ans['value_s'] - raw['value_s']) \
        <= 0.05 * raw['value_s']
    assert ans['exact'] is True     # tail reservoir answered exactly


def test_rollup_exact_tail_respects_saturation_floor():
    """A merged-tail candidate below a saturated window's reservoir
    floor must NOT be declared exact (hidden values could outrank it)."""
    from opencompass_tpu.obs import hub as hubmod
    buckets = list(hubmod.LATENCY_BUCKETS_S)
    counts = [0] * (len(buckets) + 1)
    counts[-1] = 100    # 100 observations, all in +Inf
    rollups = [{'t': 'rollup', 'series': 's', 'window_s': 60,
                'start': 0, 'labels': {}, 'count': 100, 'kept': 0,
                'errors': 0, 'buckets': buckets, 'counts': counts,
                'sum': 100.0, 'exemplars': {},
                'top': [200.0 - i for i in range(hubmod.TAIL_K)]}]
    # q=0.5 on a saturated window: rank 51-from-top is hidden
    ans = hubmod.query_rollups(rollups, 's', -1, 61, q=0.5)
    assert ans['exact'] is False
    # q=0.99 (rank 2-from-top) is inside the reservoir: exact
    ans = hubmod.query_rollups(rollups, 's', -1, 61, q=0.99)
    assert ans['exact'] is True and ans['value_s'] == 199.0


def test_reingest_is_idempotent(obs_run):
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(obs_run['root'], rate=0.0)
    hub.ingest(now=obs_run['now'], force_flush=True)
    first = hub.query(since=obs_run['t0'] - 1, until=obs_run['now'],
                      q=0.9, now=obs_run['now'])
    again = hubmod.ObsHub(obs_run['root'], rate=0.0)
    stats = again.ingest(now=obs_run['now'] + 60.0, force_flush=True)
    assert stats['ingested'] == 0    # cursors advanced durably
    second = again.query(since=obs_run['t0'] - 1, until=obs_run['now'],
                         q=0.9, now=obs_run['now'])
    assert second['count'] == first['count'] == 200
    assert second['value_s'] == first['value_s']


def test_compaction_spares_uningested_bytes(obs_run):
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(obs_run['root'], budget_bytes=1)
    hub.ingest(now=obs_run['now'], force_flush=True)
    # new records appended AFTER the ingest pass must survive compaction
    late = _mk_requests(5, obs_run['now'] + 1.0, model='late')
    _write_jsonl(osp.join(obs_run['src'], 'requests.jsonl'), late)
    monkey_ingest = hub.ingest                  # compact() re-ingests
    hub.ingest = lambda **kw: {'ingested': 0}   # ... suppress it here
    try:
        hub.compact(now=obs_run['now'])
    finally:
        hub.ingest = monkey_ingest
    assert osp.isfile(osp.join(obs_run['src'], 'requests.jsonl'))


def test_hub_exemplars_survive_to_query(obs_run):
    from opencompass_tpu.obs import hub as hubmod
    hub = hubmod.ObsHub(obs_run['root'], rate=0.0)
    hub.ingest(now=obs_run['now'], force_flush=True)
    ans = hub.query(since=obs_run['t0'] - 1, until=obs_run['now'],
                    q=0.99, now=obs_run['now'])
    assert ans.get('exemplar', '').startswith('req-tiny-')


# -- source discovery -------------------------------------------------------

def test_register_source_and_heartbeat_self_registration(tmp_path):
    from opencompass_tpu.obs import hub as hubmod
    root = str(tmp_path / 'root')
    a = osp.join(root, 'a')
    b = str(tmp_path / 'elsewhere' / 'obs')
    _write_jsonl(osp.join(a, 'requests.jsonl'),
                 _mk_requests(1, 0.0))
    os.makedirs(b)
    hubmod.register_source(root, 'hostA', 'worker', a)
    # a heartbeat carrying host/role/obs_dir joins discovery too —
    # the self-registration path runners/worker.py rides
    from opencompass_tpu.utils.fileio import atomic_write_json
    os.makedirs(osp.join(a, 'progress'), exist_ok=True)
    atomic_write_json(osp.join(a, 'progress', 'task1.json'),
                      {'v': 1, 'task': 'task1', 'ts': 0.0,
                       'state': 'running', 'host': 'hostB',
                       'role': 'worker', 'obs_dir': b})
    sources = hubmod.discover_sources(root)
    dirs = {s.obs_dir for s in sources}
    assert osp.abspath(a) in dirs and osp.abspath(b) in dirs
    roles = {s.role for s in sources}
    assert roles == {'worker'}


# -- cross-run regression attribution (acceptance) --------------------------

def _mk_run(root, name, compile_s, wall_s, shape_extra=0.0):
    """A minimal run work_dir: one perf row + a compile audit with two
    shapes, the second inflatable to inject a regression."""
    run = osp.join(root, name)
    os.makedirs(osp.join(run, 'perf', 'tiny'), exist_ok=True)
    from opencompass_tpu.utils.fileio import atomic_write_json
    atomic_write_json(osp.join(run, 'perf', 'tiny', 'mmlu.json'),
                      {'wall_seconds': wall_s, 'samples': 10,
                       'tokens_per_sec': 100.0,
                       'device_seconds': 5.0,
                       'compile_seconds': compile_s})
    _write_jsonl(osp.join(run, 'obs', 'compiles.jsonl'), [
        {'t': 'compile', 'ts': 1.0, 'shape_key': 'ppl:2x32',
         'compile_seconds': 1.0},
        {'t': 'compile', 'ts': 2.0, 'shape_key': 'gen:8x128',
         'compile_seconds': compile_s - 1.0 + shape_extra}])
    return run


def test_obs_diff_attributes_compile_regression_to_shape(tmp_path):
    """Inject a compile regression into run B; `obs diff` must rank the
    task, attribute the delta to the compile phase, and pin it on the
    inflated shape key."""
    from opencompass_tpu.obs import hub as hubmod
    root = str(tmp_path)
    run_a = _mk_run(root, 'run_a', compile_s=5.0, wall_s=60.0)
    run_b = _mk_run(root, 'run_b', compile_s=45.0, wall_s=100.0)
    report = hubmod.diff_runs(run_a, run_b)
    top = report['tasks'][0]
    assert top['key'] == 'tiny/mmlu'
    assert top['delta_s'] == pytest.approx(40.0)
    assert top['phase'] == 'compile'
    assert top['shape_key'] == 'gen:8x128'
    worst = report['shapes'][0]
    assert worst['shape_key'] == 'gen:8x128' and worst['delta_s'] > 0


def test_obs_diff_cli_renders(tmp_path, capsys):
    from opencompass_tpu.obs import hub as hubmod
    root = str(tmp_path)
    run_a = _mk_run(root, 'run_a', compile_s=5.0, wall_s=60.0)
    run_b = _mk_run(root, 'run_b', compile_s=45.0, wall_s=100.0)
    rc = hubmod.main(['diff', run_a, run_b])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'tiny/mmlu' in out and 'compile' in out \
        and 'gen:8x128' in out


def test_ledger_check_max_regression_gate(tmp_path, capsys):
    """`ledger check --max-regression` exits 2 on a wall-time
    regression and names the phase that ate the delta."""
    from opencompass_tpu.ledger import ledger as ledmod
    from opencompass_tpu.ledger.cli import main as ledger_main
    led = str(tmp_path / 'ledger')
    os.makedirs(led)
    rows = [{'v': 1, 'run': 'r1', 'model': 'tiny', 'dataset': 'mmlu',
             'wall_seconds': 100.0, 'compile_seconds': 5.0,
             'device_seconds': 40.0, 'tokens_per_sec': 100.0},
            {'v': 1, 'run': 'r2', 'model': 'tiny', 'dataset': 'mmlu',
             'wall_seconds': 160.0, 'compile_seconds': 52.0,
             'device_seconds': 40.0, 'tokens_per_sec': 100.0}]
    _write_jsonl(osp.join(led, ledmod.RUNS_FILE), rows)
    rc = ledger_main(['check', '--ledger', led,
                      '--max-regression', '0.2'])
    out = capsys.readouterr().out
    assert rc == 2
    assert 'wall 100.0s -> 160.0s' in out and 'compile phase' in out
    # under the threshold the gate passes
    assert ledger_main(['check', '--ledger', led,
                        '--max-regression', '0.9']) == 0
    capsys.readouterr()


# -- promexport staleness (satellite 2) -------------------------------------

def test_stale_gauge_withheld_from_exposition():
    from opencompass_tpu.obs.promexport import render_prometheus
    now = 10_000.0
    snap = {'gauges': {
        'fresh.value': {'value': 1.0, 'max': 2.0, 'ts': now - 10},
        'dead.value': {'value': 7.0, 'max': 9.0, 'ts': now - 9_000},
    }}
    text = render_prometheus(snap, None, now=now)
    assert 'oct_fresh_value 1' in text
    assert 'oct_dead_value 7' not in text
    assert 'oct_dead_value_max 9' in text    # max stays (monotonic)
    assert 'oct_stale_series 1' in text


def test_gauge_set_stamps_timestamp():
    from opencompass_tpu.obs.metrics import Gauge
    g = Gauge()
    g.set(3.0, now=123.0)
    assert g.last_set_ts == 123.0


def test_rollup_exposition_has_exemplars(obs_run):
    from opencompass_tpu.obs import hub as hubmod
    from opencompass_tpu.obs.promexport import render_rollup_exposition
    hub = hubmod.ObsHub(obs_run['root'], rate=0.0)
    hub.ingest(now=obs_run['now'], force_flush=True)
    text = render_rollup_exposition(hub.dir, now=obs_run['now'])
    assert 'oct_hub_completion_latency_bucket' in text
    assert '# {trace_id="req-tiny-' in text


# -- doctor disk-pressure rule (satellite 6) --------------------------------

def test_doctor_obs_disk_pressure(tmp_path, monkeypatch):
    from opencompass_tpu.obs import doctor
    src = str(tmp_path / 'obs')
    _write_jsonl(osp.join(src, 'requests.jsonl'),
                 _mk_requests(50, 0.0))
    _write_jsonl(osp.join(src, 'events.jsonl'), [])   # obs-dir marker
    monkeypatch.setenv('OCT_HUB_RETENTION_BYTES', '10')
    art = doctor.collect(src)
    assert art['hub'] and art['hub']['raw_bytes'] > 10
    findings = doctor._rule_obs_disk_pressure(art)
    assert findings and findings[0]['severity'] == 'error'
    monkeypatch.setenv('OCT_HUB_RETENTION_BYTES',
                       str(art['hub']['raw_bytes'] * 10))
    art = doctor.collect(src)
    assert doctor._rule_obs_disk_pressure(art) == []


# -- chaos deadline-skew knob (satellite 3) ---------------------------------

def test_deadline_skew_file_expires_budget(tmp_path, monkeypatch):
    from opencompass_tpu.obs import reqtrace
    skew = tmp_path / 'skew'
    skew.write_text('10.0')
    monkeypatch.setenv(reqtrace.ENV_DEADLINE_SKEW_FILE, str(skew))
    assert reqtrace.Deadline(5000).expired()
    skew.write_text('0')
    assert not reqtrace.Deadline(5000).expired()


# -- crash-safety contract (satellite 4) ------------------------------------

def test_hub_crashfuzz_contract(tmp_path):
    from opencompass_tpu.analysis import crashfuzz
    report = crashfuzz.run_hub_crashfuzz(str(tmp_path), rounds=2,
                                         n_records=60, seed=3)
    assert report['rounds'] == 2 and len(report['cuts']) == 2
