"""Dataset loaders (against synthetic fixture files), postprocessors, and
custom evaluators — all hermetic."""
import json

import pytest


# -- text postprocessors ----------------------------------------------------

def test_gsm8k_postprocessors():
    from opencompass_tpu.datasets.gsm8k import (gsm8k_dataset_postprocess,
                                                gsm8k_postprocess)
    assert gsm8k_dataset_postprocess('blah blah #### 1,234') == '1234'
    assert gsm8k_postprocess('So the answer is 42 dollars.\n\nextra') == '42'
    assert gsm8k_postprocess('no numbers here') == ''


def test_bbh_postprocessors_and_evaluator():
    from opencompass_tpu.datasets.bbh import (BBHEvaluator,
                                              bbh_freeform_postprocess,
                                              bbh_mcq_postprocess)
    assert bbh_mcq_postprocess('the answer is (B).') == 'B'
    assert bbh_mcq_postprocess('the answer is C') == 'C'
    assert bbh_freeform_postprocess('the answer is valid.') == 'valid'
    res = BBHEvaluator().score(['the answer is yes', 'the answer is no'],
                               ['yes', 'yes'])
    assert res['score'] == 50.0


def test_math_extraction_and_equivalence():
    from opencompass_tpu.datasets.math import (MATHEvaluator,
                                               last_boxed_answer,
                                               math_postprocess)
    assert last_boxed_answer(r'text \boxed{\frac{1}{2}} more') == \
        r'\frac{1}{2}'
    assert last_boxed_answer('no box') is None
    ev = MATHEvaluator()
    assert ev.is_equiv('1/2', '\\frac{1}{2}')
    assert ev.is_equiv('0.5', '\\frac{1}{2}')
    assert ev.is_equiv('\\tfrac{1}{2}', '\\frac{1}{2}')
    assert not ev.is_equiv('2', '3')
    assert 'accuracy' in ev.score(['1/2'], ['\\frac{1}{2}'])
    out = math_postprocess('The final answer is $\\frac{3}{4}$.')
    assert out == '\\frac{3}{4}'


def test_humaneval_evaluator_and_postprocess():
    from opencompass_tpu.datasets.humaneval import (HumanEvaluator,
                                                    humaneval_postprocess,
                                                    pass_at_k)
    problem = {
        'prompt': 'def add(a, b):\n',
        'test': 'def check(f):\n    assert f(1, 2) == 3\n',
        'entry_point': 'add',
    }
    good = '    return a + b\n'
    bad = '    return a - b\n'
    res = HumanEvaluator(k=[1]).score([good, bad], [problem, problem])
    assert res['humaneval_pass@1'] == 50.0
    assert pass_at_k(10, 10, 1) == 1.0
    assert pass_at_k(10, 0, 5) == 0.0
    assert humaneval_postprocess('return 1\n\nrest').startswith('    ')


def test_mbpp_evaluator():
    from opencompass_tpu.datasets.mbpp import MBPPEvaluator
    tests = 'assert add(1, 2) == 3'
    good = '[BEGIN]def add(a, b):\n    return a + b[DONE]'
    wrong = 'def add(a, b):\n    return a - b'
    broken = 'def add(a, b) return'
    res = MBPPEvaluator().score([good, wrong, broken],
                                [tests, tests, tests])
    assert res['pass'] == 1 and res['wrong_answer'] == 1 \
        and res['failed'] == 1
    assert abs(res['score'] - 100 / 3) < 1e-6


def test_trivia_nq_evaluators():
    from opencompass_tpu.datasets.natural_question import NQEvaluator
    from opencompass_tpu.datasets.triviaqa import TriviaQAEvaluator
    res = TriviaQAEvaluator().score(
        ['The answer is Paris.', 'London\nmore text'],
        [['paris', 'the city of light'], ['Berlin']])
    assert res['score'] == 50.0
    res = NQEvaluator().score(['paris'], [['Paris']])
    assert res['score'] == 100.0


def test_lambada_evaluator():
    from opencompass_tpu.datasets.lambada import LambadaEvaluator
    res = LambadaEvaluator().score(['word, extra', 'wrong'],
                                   ['word', 'right'])
    assert res['accuracy'] == 50.0


def test_strategyqa_postprocessors():
    from opencompass_tpu.datasets.strategyqa import (
        strategyqa_dataset_postprocess, strategyqa_pred_postprocess)
    assert strategyqa_pred_postprocess('So the answer is Yes.') == 'yes'
    assert strategyqa_dataset_postprocess('True') == 'yes'
    assert strategyqa_dataset_postprocess('False') == 'no'


def test_gaokao_evaluator():
    from opencompass_tpu.datasets.GaokaoBench import GaokaoBenchEvaluator
    ev = GaokaoBenchEvaluator('single_choice')
    res = ev.score(['所以选B', '答案是A'], [['B'], ['C']])
    assert res['score'] == 50.0
    ev = GaokaoBenchEvaluator('multi_choice')
    # exact (2/2) + subset partial credit (1/2)
    res = ev.score(['【答案】AB', '【答案】A'], [['AB'], ['AB']])
    assert res['score'] == 75.0


def test_agieval_parse_and_evaluator():
    from opencompass_tpu.datasets.agieval import (AGIEvalEvaluator,
                                                  first_capital_letter,
                                                  parse_math_answer)
    assert parse_math_answer(r'stuff \boxed{42}') == '42'
    assert parse_math_answer('x = 7') == '7'
    assert parse_math_answer('the result is $y=3$') == '3'
    assert first_capital_letter('answer: C') == 'C'
    res = AGIEvalEvaluator().score([r'\boxed{1/2}'], ['\\frac{1}{2}'])
    assert res['score'] == 100.0


def test_truthfulqa_evaluator():
    from opencompass_tpu.datasets.truthfulqa import TruthfulQAEvaluator
    refs = [{'answers': {'best_answer': 'the sky is blue',
                         'correct_answers': ['the sky is blue'],
                         'incorrect_answers': ['the sky is green']}}]
    res = TruthfulQAEvaluator().score(['the sky is blue'], refs)
    assert res['f1_acc'] == 100.0
    assert res['f1_max'] == 100.0


# -- loaders over synthetic fixture files -----------------------------------

def test_mmlu_loader(tmp_path):
    from opencompass_tpu.datasets.mmlu import MMLUDataset
    for split in ('dev', 'test'):
        d = tmp_path / split
        d.mkdir()
        (d / f'anatomy_{split}.csv').write_text(
            '"What is 1+1?","1","2","3","4","B"\n')
    ds = MMLUDataset.load(str(tmp_path), 'anatomy')
    assert ds['test'][0]['target'] == 'B'
    assert ds['dev'][0]['A'] == '1'


def test_arc_loader(tmp_path):
    from opencompass_tpu.datasets.arc import ARCDataset
    rows = [
        {'answerKey': 'B', 'question': {
            'stem': 'Q1', 'choices': [{'text': f'c{i}'} for i in range(4)]}},
        {'answerKey': 'A', 'question': {
            'stem': 'Q2', 'choices': [{'text': 'x'}] * 3}},  # dropped
    ]
    p = tmp_path / 'arc.jsonl'
    p.write_text('\n'.join(json.dumps(r) for r in rows))
    ds = ARCDataset.load(str(p))
    assert len(ds) == 1
    assert ds[0]['textC'] == 'c2'


def test_boolq_copa_wsc_v2_loaders(tmp_path):
    from opencompass_tpu.datasets.boolq import BoolQDataset_V2
    from opencompass_tpu.datasets.copa import COPADataset_V2
    from opencompass_tpu.datasets.wsc import WSCDataset_V2
    p = tmp_path / 'boolq.jsonl'
    p.write_text(json.dumps({'label': 'true', 'passage': 'p',
                             'question': 'q'}) + '\n')
    assert BoolQDataset_V2.load(str(p))[0]['label'] == 'A'
    p = tmp_path / 'copa.jsonl'
    p.write_text(json.dumps({'label': 1, 'premise': 'p', 'choice1': 'a',
                             'choice2': 'b', 'question': 'cause'}) + '\n')
    assert COPADataset_V2.load(str(p))[0]['label'] == 'B'
    p = tmp_path / 'wsc.jsonl'
    p.write_text(json.dumps({
        'text': 'the cat sat', 'label': 'false',
        'target': {'span1_text': 'cat', 'span1_index': 1,
                   'span2_text': 'it', 'span2_index': 2}}) + '\n')
    row = WSCDataset_V2.load(str(p))[0]
    assert row['label'] == 'B' and row['span1'] == 'cat'


def test_record_multirc_loaders(tmp_path):
    from opencompass_tpu.datasets.multirc import MultiRCDataset_V2
    from opencompass_tpu.datasets.record import ReCoRDDataset
    p = tmp_path / 'record.jsonl'
    p.write_text(json.dumps({
        'passage': {'text': 'text @highlight more'},
        'qas': [{'query': 'X @placeholder Y',
                 'answers': [{'text': 'ans'}]}]}) + '\n')
    row = ReCoRDDataset.load(str(p))[0]
    assert '____' in row['question'] and '@highlight' not in row['text']
    p = tmp_path / 'multirc.jsonl'
    p.write_text(json.dumps({
        'passage': {'text': 't', 'questions': [
            {'question': 'q',
             'answers': [{'text': 'a', 'label': 1}]}]}}) + '\n')
    assert MultiRCDataset_V2.load(str(p))[0]['label'] == 'A'


def test_c3_chid_loaders(tmp_path):
    from opencompass_tpu.datasets.c3 import C3Dataset_V2
    from opencompass_tpu.datasets.chid import CHIDDataset_V2
    p = tmp_path / 'c3.json'
    p.write_text(json.dumps([
        [[['para one'], ['para two']],
         [{'question': 'q', 'choice': ['a', 'b'], 'answer': 'b'}]],
    ]))
    row = C3Dataset_V2.load(str(p))[0]
    assert row['label'] == 'B' and row['choice3'] == '[NULL]'
    p = tmp_path / 'chid.jsonl'
    p.write_text(json.dumps({
        'content': 'x#idiom#y', 'candidates': ['一', '二'],
        'answer': 1}) + '\n')
    row = CHIDDataset_V2.load(str(p))[0]
    assert row['answer'] == 'B' and '______' in row['content']


def test_cmrc_loader_and_postprocess(tmp_path):
    from opencompass_tpu.datasets.cmrc import CMRCDataset, cmrc_postprocess
    p = tmp_path / 'cmrc.json'
    p.write_text(json.dumps({'data': [
        {'paragraphs': [{'context': 'ctx', 'qas': [
            {'question': 'q',
             'answers': [{'text': 'a'}, {'text': 'a'}]}]}]},
    ]}))
    row = CMRCDataset.load(str(p))[0]
    assert row['answers'] == ['a']
    assert cmrc_postprocess('所以答案是北京') == '北京'


def test_gaokao_agieval_math_loaders(tmp_path):
    from opencompass_tpu.datasets.agieval import AGIEvalDataset_v2
    from opencompass_tpu.datasets.GaokaoBench import GaokaoBenchDataset
    from opencompass_tpu.datasets.math import MATHDataset
    p = tmp_path / 'gaokao.json'
    p.write_text(json.dumps({'example': [{'question': 'q',
                                          'answer': ['A']}]}))
    assert GaokaoBenchDataset.load(str(p))[0]['answer'] == ['A']
    p = tmp_path / 'agi.jsonl'
    p.write_text(json.dumps({'passage': 'P. ', 'question': 'Q?',
                             'options': ['(A) x', '(B) y'],
                             'label': 'A'}) + '\n')
    ds = AGIEvalDataset_v2.load(str(tmp_path), 'agi')
    assert ds[0]['question'].startswith('P. ')
    p = tmp_path / 'math.json'
    p.write_text(json.dumps({'0': {
        'problem': 'what?', 'solution': 'thus \\boxed{42}'}}))
    assert MATHDataset.load(str(p)).reader is not None \
        if hasattr(MATHDataset.load(str(p)), 'reader') \
        else MATHDataset.load(str(p))['test'][0]['solution'] == '42'


def test_gsm8k_humaneval_loaders(tmp_path):
    from opencompass_tpu.datasets.gsm8k import GSM8KDataset
    from opencompass_tpu.datasets.humaneval import HumanEvalDataset
    for split in ('train', 'test'):
        (tmp_path / f'{split}.jsonl').write_text(
            json.dumps({'question': 'q', 'answer': 'a #### 5'}) + '\n')
    ds = GSM8KDataset.load(str(tmp_path))
    assert ds['test'][0]['answer'].endswith('5')
    p = tmp_path / 'he.jsonl'
    p.write_text(json.dumps({'task_id': 'HumanEval/0', 'prompt': 'def f():',
                             'test': 'def check(f): pass',
                             'entry_point': 'f'}) + '\n')
    assert HumanEvalDataset.load(str(p))['test'][0]['entry_point'] == 'f'


def test_clue_loaders(tmp_path):
    from opencompass_tpu.datasets.clue_fewclue import (AFQMCDataset_V2,
                                                       TNewsDataset_V2,
                                                       cmnliDataset_V2,
                                                       eprstmtDataset_V2)
    p = tmp_path / 'afqmc.jsonl'
    p.write_text(json.dumps({'sentence1': 'a', 'sentence2': 'b',
                             'label': '1'}) + '\n')
    assert AFQMCDataset_V2.load(str(p))[0]['label'] == 'B'
    p = tmp_path / 'eprstmt.jsonl'
    p.write_text(json.dumps({'sentence': 's', 'label': 'Negative'}) + '\n')
    assert eprstmtDataset_V2.load(str(p))[0]['label'] == 'B'
    p = tmp_path / 'cmnli.jsonl'
    p.write_text(json.dumps({'sentence1': 'a', 'sentence2': 'b',
                             'label': 'neutral'}) + '\n' +
                 json.dumps({'sentence1': 'x', 'sentence2': 'y',
                             'label': '-'}) + '\n')
    ds = cmnliDataset_V2.load(str(p))
    assert len(ds) == 1 and ds[0]['label'] == 'C'
    p = tmp_path / 'tnews.jsonl'
    p.write_text(json.dumps({'sentence': 's',
                             'label_desc': 'news_game'}) + '\n')
    assert TNewsDataset_V2.load(str(p))[0]['label_desc2'] == 'C'


def test_summedits_xsum_safety_loaders(tmp_path):
    from opencompass_tpu.datasets.summedits import SummeditsDataset_V2
    from opencompass_tpu.datasets.toxicity import SafetyDataset
    from opencompass_tpu.datasets.xsum import XsumDataset
    p = tmp_path / 'se.jsonl'
    p.write_text(json.dumps({'doc': 'd', 'summary': 's', 'label': 1})
                 + '\n')
    assert SummeditsDataset_V2.load(str(p))[0]['label'] == 'A'
    p = tmp_path / 'xsum.jsonl'
    p.write_text(json.dumps({'dialogue': 'd', 'summary': 's'}) + '\n')
    assert XsumDataset.load(str(p))[0]['summary'] == 's'
    p = tmp_path / 'safety.txt'
    p.write_text('prompt one\n\nprompt two\n')
    assert len(SafetyDataset.load(str(p))['test']) == 2


def test_ceval_loader(tmp_path):
    from opencompass_tpu.datasets.ceval import CEvalDataset
    header = 'id,question,A,B,C,D,answer,explanation\n'
    for split, extra in (('dev', '0,q,1,2,3,4,B,why\n'),
                         ('val', None), ('test', None)):
        d = tmp_path / split
        d.mkdir()
        if split == 'dev':
            (d / f'law_{split}.csv').write_text(header + extra)
        elif split == 'val':
            (d / f'law_{split}.csv').write_text(
                'id,question,A,B,C,D,answer\n0,q,1,2,3,4,A\n')
        else:
            (d / f'law_{split}.csv').write_text(
                'id,question,A,B,C,D\n0,q,1,2,3,4\n')
    ds = CEvalDataset.load(str(tmp_path), 'law')
    assert ds['dev'][0]['answer'] == 'B'
    assert ds['test'][0]['answer'] == ''
    assert ds['val'][0]['explanation'] == ''
