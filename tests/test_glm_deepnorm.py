"""GLM-130B DeepNorm block math + SAT checkpoint conversion.

The reference runs the real GLM-130B through the external SAT package
(reference opencompass/models/glm.py:34-120).  Real 130B weights cannot
be fetched here, so parity is pinned the same way as the ChatGLM
families (tests/test_chatglm_parity.py): an in-test torch
reimplementation of the GLM block — DeepNorm residuals (post-LN,
alpha=(2L)^0.5), GeGLU (first h_to_4h half GELU-gated), 1D rotate-half
RoPE, prefix-LM mask — runs the SAME weights as the JAX stack, loaded
from a synthetic SAT-format model-parallel checkpoint, and the logits
must agree.  This validates the converter's shard-merge rules and the
deepnorm execution path in one shot.
"""
import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip('torch')

from opencompass_tpu.nn import TransformerConfig, forward, greedy_generate
from opencompass_tpu.nn.sat_convert import (convert_sat_checkpoint,
                                            is_sat_checkpoint)

H, L, NH, V, F, MP = 32, 2, 4, 512, 48, 2  # V >= 259: byte-tokenizer floor
HD = H // NH


def _tiny_cfg():
    return TransformerConfig.glm130b(
        vocab_size=V, hidden_size=H, num_layers=L, num_heads=NH,
        intermediate_size=F, max_seq_len=64, dtype='float32')


def _make_sat_dir(tmpdir) -> str:
    """Synthetic 2-way model-parallel SAT checkpoint with random weights,
    sharded exactly the way megatron shards GLM-130B."""
    g = torch.Generator().manual_seed(0)

    def t(*shape):
        return torch.randn(*shape, generator=g) * 0.1

    embed = t(V, H)
    full = {'transformer.word_embeddings.weight': embed,
            'transformer.final_layernorm.weight': 1 + 0.1 * t(H),
            'transformer.final_layernorm.bias': 0.1 * t(H)}
    per_layer = []
    for i in range(L):
        p = f'transformer.layers.{i}.'
        lw = {
            p + 'input_layernorm.weight': 1 + 0.1 * t(H),
            p + 'input_layernorm.bias': 0.1 * t(H),
            p + 'post_attention_layernorm.weight': 1 + 0.1 * t(H),
            p + 'post_attention_layernorm.bias': 0.1 * t(H),
            p + 'attention.query_key_value.weight': t(3 * H, H),
            p + 'attention.query_key_value.bias': t(3 * H),
            p + 'attention.dense.weight': t(H, H),
            p + 'attention.dense.bias': t(H),
            p + 'mlp.dense_h_to_4h.weight': t(2 * F, H),
            p + 'mlp.dense_h_to_4h.bias': t(2 * F),
            p + 'mlp.dense_4h_to_h.weight': t(H, F),
            p + 'mlp.dense_4h_to_h.bias': t(H),
        }
        per_layer.append(lw)
        full.update(lw)

    # shard like megatron: vocab dim0 for embeddings; qkv/h_to_4h
    # column-parallel with per-shard [q;k;v] / [gate;up] stacking;
    # dense/4h_to_h row-parallel; norms replicated
    shards = [dict() for _ in range(MP)]
    for r in range(MP):
        shards[r]['transformer.word_embeddings.weight'] = \
            embed.chunk(MP, 0)[r]
        for key in ('transformer.final_layernorm.weight',
                    'transformer.final_layernorm.bias'):
            shards[r][key] = full[key]
    for i in range(L):
        p = f'transformer.layers.{i}.'
        for key in ('input_layernorm.weight', 'input_layernorm.bias',
                    'post_attention_layernorm.weight',
                    'post_attention_layernorm.bias',
                    'attention.dense.bias', 'mlp.dense_4h_to_h.bias'):
            for r in range(MP):
                shards[r][p + key] = full[p + key]
        qf, kf, vf = full[p + 'attention.query_key_value.weight'] \
            .chunk(3, 0)
        qb, kb, vb = full[p + 'attention.query_key_value.bias'].chunk(3, 0)
        gf, uf = full[p + 'mlp.dense_h_to_4h.weight'].chunk(2, 0)
        gb, ub = full[p + 'mlp.dense_h_to_4h.bias'].chunk(2, 0)
        for r in range(MP):
            shards[r][p + 'attention.query_key_value.weight'] = torch.cat(
                [qf.chunk(MP, 0)[r], kf.chunk(MP, 0)[r],
                 vf.chunk(MP, 0)[r]], 0)
            shards[r][p + 'attention.query_key_value.bias'] = torch.cat(
                [qb.chunk(MP, 0)[r], kb.chunk(MP, 0)[r],
                 vb.chunk(MP, 0)[r]], 0)
            shards[r][p + 'mlp.dense_h_to_4h.weight'] = torch.cat(
                [gf.chunk(MP, 0)[r], uf.chunk(MP, 0)[r]], 0)
            shards[r][p + 'mlp.dense_h_to_4h.bias'] = torch.cat(
                [gb.chunk(MP, 0)[r], ub.chunk(MP, 0)[r]], 0)
            shards[r][p + 'attention.dense.weight'] = \
                full[p + 'attention.dense.weight'].chunk(MP, 1)[r]
            shards[r][p + 'mlp.dense_4h_to_h.weight'] = \
                full[p + 'mlp.dense_4h_to_h.weight'].chunk(MP, 1)[r]

    path = str(tmpdir)
    for r, module in enumerate(shards):
        torch.save({'module': module},
                   os.path.join(path, f'mp_rank_{r:02d}_model_states.pt'))
    return path, full


def _torch_forward(full, tokens, prefix_len):
    """Reference GLM block stack in torch float32."""
    B, S = tokens.shape
    alpha = (2.0 * L) ** 0.5
    x = full['transformer.word_embeddings.weight'][tokens]
    positions = torch.arange(S)

    # rotate-half RoPE, full head dim, theta 1e4
    freqs = (10000.0 ** (-torch.arange(0, HD // 2, dtype=torch.float32)
                         / (HD // 2)))
    ang = positions[:, None].float() * freqs            # (S, HD/2)
    cos, sin = torch.cos(ang), torch.sin(ang)

    def rope(z):                                        # (B,S,NH,HD)
        z1, z2 = z[..., :HD // 2], z[..., HD // 2:]
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return torch.cat([z1 * c - z2 * s, z2 * c + z1 * s], -1)

    causal = torch.tril(torch.ones(S, S, dtype=torch.bool))
    prefix = torch.zeros(S, dtype=torch.bool)
    prefix[:prefix_len] = True
    mask = causal | prefix[None, :]

    def ln(z, w, b):
        mu = z.mean(-1, keepdim=True)
        var = ((z - mu) ** 2).mean(-1, keepdim=True)
        return (z - mu) / torch.sqrt(var + 1e-5) * w + b

    for i in range(L):
        p = f'transformer.layers.{i}.'
        h = ln(x, full[p + 'input_layernorm.weight'],
               full[p + 'input_layernorm.bias'])
        qkv = h @ full[p + 'attention.query_key_value.weight'].T \
            + full[p + 'attention.query_key_value.bias']
        q, k, v = qkv.chunk(3, -1)
        q = rope(q.view(B, S, NH, HD))
        k = rope(k.view(B, S, NH, HD))
        v = v.view(B, S, NH, HD)
        scores = torch.einsum('bqhd,bkhd->bhqk', q, k) * HD ** -0.5
        scores = scores.masked_fill(~mask[None, None], -1e30)
        attn = torch.einsum('bhqk,bkhd->bqhd', scores.softmax(-1), v)
        attn = attn.reshape(B, S, H) \
            @ full[p + 'attention.dense.weight'].T \
            + full[p + 'attention.dense.bias']
        x = h * alpha + attn                            # DeepNorm
        h2 = ln(x, full[p + 'post_attention_layernorm.weight'],
                full[p + 'post_attention_layernorm.bias'])
        gup = h2 @ full[p + 'mlp.dense_h_to_4h.weight'].T \
            + full[p + 'mlp.dense_h_to_4h.bias']
        gate, up = gup.chunk(2, -1)
        mlp = (torch.nn.functional.gelu(gate) * up) \
            @ full[p + 'mlp.dense_4h_to_h.weight'].T \
            + full[p + 'mlp.dense_4h_to_h.bias']
        x = h2 * alpha + mlp                            # DeepNorm
    x = ln(x, full['transformer.final_layernorm.weight'],
           full['transformer.final_layernorm.bias'])
    return x @ full['transformer.word_embeddings.weight'].T


def test_sat_convert_and_deepnorm_parity(tmp_path):
    path, full = _make_sat_dir(tmp_path)
    assert is_sat_checkpoint(path)
    cfg = _tiny_cfg()
    cfg2, params = convert_sat_checkpoint(path, cfg)
    assert params['layers']['q']['w'].shape == (L, H, H)
    assert params['embed'].shape == (V, H)

    rng = np.random.RandomState(0)
    B, S, PFX = 2, 12, 5
    tokens = rng.randint(0, V, (B, S))
    mask = np.ones((B, S), bool)
    prefix = np.zeros((B, S), bool)
    prefix[:, :PFX] = True

    got = np.asarray(forward(params, cfg2, jnp.asarray(tokens),
                             jnp.asarray(mask), use_flash=False,
                             prefix_mask=jnp.asarray(prefix)))
    want = _torch_forward({k: v for k, v in full.items()},
                          torch.from_numpy(tokens), PFX).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_deepnorm_differs_from_prenorm():
    """The deepnorm flag must actually change the math (guards against a
    silently ignored config field)."""
    cfg = _tiny_cfg()
    from opencompass_tpu.nn import init_params
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, V, (1, 8)))
    mask = jnp.ones((1, 8), bool)
    a = np.asarray(forward(params, cfg, tokens, mask, use_flash=False))
    b = np.asarray(forward(params,
                           dataclasses.replace(cfg, deepnorm=False),
                           tokens, mask, use_flash=False))
    assert np.abs(a - b).max() > 1e-3


def test_glm130b_decode_runs_with_deepnorm():
    cfg = _tiny_cfg()
    from opencompass_tpu.nn import init_params
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, V, (2, 8)))
    mask = jnp.ones((2, 8), bool)
    out, lengths = jax.jit(lambda p, t, m: greedy_generate(
        p, cfg, t, m, 6))(params, tokens, mask)
    assert out.shape == (2, 6)
    # prefill treats the whole prompt as bidirectional prefix-LM context
    # (nn/transformer.py prefill, GLM [gMASK] semantics) — compare against
    # the parallel forward with the same prefix mask
    logits = forward(params, cfg, tokens, mask, use_flash=False,
                     prefix_mask=mask)
    first = np.asarray(jnp.argmax(logits[:, -1], -1))
    assert (np.asarray(out)[:, 0] == first).all()


def test_sat_convert_cache_roundtrip(tmp_path):
    """Second conversion with a cache_dir must serve identical arrays
    from disk instead of re-merging the torch shards."""
    from opencompass_tpu.nn.sat_convert import convert_sat_checkpoint_cached
    (tmp_path / 'ckpt').mkdir(exist_ok=True)
    path, _ = _make_sat_dir(tmp_path / 'ckpt')
    cache = str(tmp_path / 'cache')
    cfg = _tiny_cfg()
    _, p1 = convert_sat_checkpoint_cached(path, cfg, cache_dir=cache)
    assert any(d.startswith('sat_') for d in os.listdir(cache))
    _, p2 = convert_sat_checkpoint_cached(path, cfg, cache_dir=cache)
    np.testing.assert_array_equal(np.asarray(p1['embed'], np.float32),
                                  np.asarray(p2['embed'], np.float32))
    np.testing.assert_array_equal(
        np.asarray(p1['layers']['q']['w'], np.float32),
        np.asarray(p2['layers']['q']['w'], np.float32))


def test_jaxlm_loads_sat_checkpoint(tmp_path):
    path, _ = _make_sat_dir(tmp_path)
    from opencompass_tpu.models import GLM130B
    lm = GLM130B(path=path,
                 config=dict(preset='glm130b', vocab_size=V, hidden_size=H,
                             num_layers=L, num_heads=NH,
                             intermediate_size=F, max_seq_len=64,
                             dtype='float32'),
                 max_seq_len=64, parallel=dict(data=1, model=1, seq=1))
    nll = lm.get_ppl(['ab'])
    assert np.isfinite(nll[0])
