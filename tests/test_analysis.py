"""The analysis subsystem (ISSUE 13): oct-lint rules + pragma/baseline
triage, the repo-wide lint CI gate, the racecheck lock-order sanitizer
(incl. an inversion reproducer and an instrumented engine run), and
the crashfuzz crash-consistency suite over every journal contract.
"""
import json
import os
import os.path as osp
import subprocess
import sys
import threading

import pytest

from opencompass_tpu.analysis import crashfuzz
from opencompass_tpu.analysis.linter import (RULES, load_baseline, main,
                                             run_lint, update_baseline)
from opencompass_tpu.analysis.racecheck import (LockOrderInversion,
                                                RaceCheck)

FIXTURES = osp.join(osp.dirname(__file__), 'fixtures', 'lint')
CHECKED_RULES = [r for r in RULES if r != 'OCT000']


# -- per-rule fixtures -------------------------------------------------------

@pytest.mark.parametrize('rule', CHECKED_RULES)
def test_rule_fires_on_fixture(rule):
    path = osp.join(FIXTURES, f'{rule.lower()}_fire.py')
    report = run_lint([path], baseline_path=None)
    fired = [f.rule for f in report.active]
    assert rule in fired, f'{rule} did not fire on {path}: {fired}'
    assert set(fired) == {rule}, (
        f'fixture for {rule} trips other rules too: {fired}')


@pytest.mark.parametrize('rule', CHECKED_RULES)
def test_rule_passes_clean_fixture(rule):
    path = osp.join(FIXTURES, f'{rule.lower()}_clean.py')
    report = run_lint([path], baseline_path=None)
    assert report.active == [], (
        f'clean fixture for {rule} still fires: '
        f'{[f.render() for f in report.active]}')


# -- pragma triage -----------------------------------------------------------

def test_pragma_with_reason_suppresses(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text(
        "import json\n"
        "def save(path, state):\n"
        "    with open(path, 'w') as f:\n"
        "        # oct-lint: disable=OCT002(demo state, single process)\n"
        "        json.dump(state, f)\n")
    report = run_lint([str(src)], baseline_path=None)
    assert report.active == []
    assert report.pragma_count == 1


def test_pragma_without_reason_is_oct000(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text(
        "import json\n"
        "def save(path, state):\n"
        "    # oct-lint: disable=OCT002\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(state, f)\n")
    report = run_lint([str(src)], baseline_path=None)
    rules = sorted(f.rule for f in report.active)
    # the reasonless pragma does NOT suppress, and is itself flagged
    assert rules == ['OCT000', 'OCT002']


def test_pragma_on_continuation_line_suppresses(tmp_path):
    """A pragma on ANY line of a multi-line statement suppresses a
    finding anchored to the statement's first line."""
    src = tmp_path / 'mod.py'
    src.write_text(
        "import os\n"
        "def f(path):\n"
        "    fd = os.open(path,\n"
        "                 os.O_WRONLY | os.O_APPEND)"
        "  # oct-lint: disable=OCT001(seal writer)\n"
        "    os.close(fd)\n")
    report = run_lint([str(src)], baseline_path=None)
    assert report.active == []


def test_oct005_requires_exact_fallback_shape(tmp_path):
    """An arbitrary ternary must not exempt a wall-clock read — only
    the `time.time() if now is None else now` sentinel shape (and its
    inverse) passes."""
    src = tmp_path / 'mod.py'
    src.write_text(
        "# oct-lint: clock-discipline\n"
        "import time\n"
        "def f(t0, flag, now=None, ts=None):\n"
        "    a = (time.time() - t0) if flag else 0.0\n"
        "    b = time.time() if now is not None else now\n"
        "    good = time.time() if now is None else now\n"
        "    also = ts if ts is not None else time.time()\n"
        "    return a, b, good, also\n")
    report = run_lint([str(src)], baseline_path=None)
    assert sorted(f.line for f in report.active) == [4, 5]


def test_oct002_module_scope_not_exempted_by_helper(tmp_path):
    """A helper function's os.replace must not exempt module-level
    json.dump-into-open('w')."""
    src = tmp_path / 'mod.py'
    src.write_text(
        "import json, os\n"
        "def helper(tmp, path):\n"
        "    os.replace(tmp, path)\n"
        "with open('state.json', 'w') as f:\n"
        "    json.dump({}, f)\n")
    report = run_lint([str(src)], baseline_path=None)
    assert [f.rule for f in report.active] == ['OCT002']


def test_stale_baseline_scoped_to_run_and_pruned(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text("import json\n"
                   "def save(path, state):\n"
                   "    with open(path, 'w') as f:\n"
                   "        json.dump(state, f)\n")
    base = tmp_path / 'baseline.json'
    report = run_lint([str(src)], baseline_path=None)
    update_baseline(report, str(base), 'triaged')
    # a --rules subset that does not cover OCT002 must not call the
    # entry stale
    report = run_lint([str(src)], baseline_path=str(base),
                      rules=['OCT005'])
    assert report.stale_baseline == []
    # fix the code: full run reports the entry stale, and re-running
    # --update-baseline prunes it
    src.write_text('x = 1\n')
    report = run_lint([str(src)], baseline_path=str(base))
    assert len(report.stale_baseline) == 1
    update_baseline(report, str(base), 'unused')
    index, _ = load_baseline(str(base))
    assert index == {}


def test_pragma_reason_may_contain_parentheses(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text(
        "import json\n"
        "def save(path, state):\n"
        "    with open(path, 'w') as f:\n"
        "        # oct-lint: disable=OCT002(single process, see "
        "save() docs)\n"
        "        json.dump(state, f)\n")
    report = run_lint([str(src)], baseline_path=None)
    assert report.active == [], [f.render() for f in report.active]
    assert report.pragma_count == 1


def test_oct005_catches_import_aliases(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text(
        "# oct-lint: clock-discipline\n"
        "from time import time\n"
        "import time as t\n"
        "def f():\n"
        "    return time() + t.time()\n")
    report = run_lint([str(src)], baseline_path=None)
    assert len(report.active) == 2
    assert {f.rule for f in report.active} == {'OCT005'}


def test_oct004_join_must_be_in_scope_and_thread_style(tmp_path):
    """An unrelated same-named handle's join in ANOTHER scope, or a
    str.join(parts), must not silence a never-joined thread; a real
    join() / join(timeout=) in the same scope does."""
    src = tmp_path / 'mod.py'
    src.write_text(
        "import threading\n"
        "class A:\n"
        "    def start(self, fn):\n"
        "        self._reaper = threading.Thread(target=fn)\n"
        "        self._reaper.start()\n"
        "class B:\n"
        "    def stop(self):\n"
        "        self._reaper.join()\n"
        "def strjoin(fn, t):\n"
        "    th = threading.Thread(target=fn)\n"
        "    th.start()\n"
        "    return t.join(['a'])\n"
        "def ok(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
        "    t.join(timeout=5)\n")
    report = run_lint([str(src)], baseline_path=None)
    lines = sorted(f.line for f in report.active)
    assert {f.rule for f in report.active} == {'OCT004'}
    assert lines == [4, 10], [f.render() for f in report.active]


def test_nonexistent_path_fails_check(tmp_path):
    report = run_lint([str(tmp_path / 'no_such_dir')],
                      baseline_path=None)
    assert report.parse_errors
    assert main([str(tmp_path / 'no_such_dir'), '--check']) == 2


def test_pragma_in_docstring_is_ignored(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text('"""Docs may mention # oct-lint: disable=OCT001'
                   '(x) freely."""\n')
    report = run_lint([str(src)], baseline_path=None)
    assert report.active == []


# -- baseline triage ---------------------------------------------------------

def test_baseline_suppresses_only_with_reason(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text("import json\n"
                   "def save(path, state):\n"
                   "    with open(path, 'w') as f:\n"
                   "        json.dump(state, f)\n")
    base = tmp_path / 'baseline.json'
    rel = osp.basename(str(src))
    base.write_text(json.dumps({'v': 1, 'entries': [
        {'rule': 'OCT002', 'path': rel,
         'line_text': 'json.dump(state, f)', 'reason': 'triaged demo'},
    ]}))
    report = run_lint([str(src)], baseline_path=str(base))
    assert report.active == []
    assert len(report.baselined) == 1
    # strip the reason → entry stops suppressing and is flagged OCT000
    base.write_text(json.dumps({'v': 1, 'entries': [
        {'rule': 'OCT002', 'path': rel,
         'line_text': 'json.dump(state, f)', 'reason': ''},
    ]}))
    report = run_lint([str(src)], baseline_path=str(base))
    assert sorted(f.rule for f in report.active) == ['OCT000', 'OCT002']


def test_update_baseline_roundtrip(tmp_path):
    src = tmp_path / 'mod.py'
    src.write_text("import json\n"
                   "def save(path, state):\n"
                   "    with open(path, 'w') as f:\n"
                   "        json.dump(state, f)\n")
    base = tmp_path / 'baseline.json'
    report = run_lint([str(src)], baseline_path=None)
    assert len(report.active) == 1
    update_baseline(report, str(base), 'accepted for the demo')
    index, bad = load_baseline(str(base))
    assert len(index) == 1 and not bad
    report = run_lint([str(src)], baseline_path=str(base))
    assert report.active == [] and len(report.baselined) == 1
    # stale entries are reported once the code is fixed
    src.write_text('x = 1\n')
    report = run_lint([str(src)], baseline_path=str(base))
    assert len(report.stale_baseline) == 1


# -- the repo gate (tier-1 CI: `cli lint --check` convention) ----------------

def test_repo_is_lint_clean():
    """The package must lint clean: every remaining finding is either
    fixed, pragma'd with a reason, or baselined with a reason — the
    acceptance bar for every future PR (same CI role as `ledger
    check` / `doctor --check`)."""
    report = run_lint()     # default paths + committed baseline
    assert report.parse_errors == []
    assert report.active == [], (
        'unbaselined oct-lint findings:\n  '
        + '\n  '.join(f.render() for f in report.active))


def test_lint_main_check_exit_codes(tmp_path):
    # clean repo → 0 under --check
    assert main(['--check']) == 0
    # a firing file with no baseline → 2 under --check, 0 without
    fire = osp.join(FIXTURES, 'oct001_fire.py')
    assert main([fire, '--baseline', 'none']) == 0
    assert main([fire, '--baseline', 'none', '--check']) == 2
    # --json emits a parseable report (captured via a file redirect
    # in the CLI smoke below; here exercise the dict path)
    report = run_lint([fire], baseline_path=None)
    doc = report.to_dict()
    assert doc['by_rule'].get('OCT001') == 2
    assert doc['active'] == 2


def test_cli_lint_subcommand_smoke():
    """`python -m opencompass_tpu.cli lint --check --json` wires
    through the CLI dispatcher and exits 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'lint',
         '--check', '--json'],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS='cpu'),
        cwd=osp.dirname(osp.dirname(osp.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc['active'] == 0
    assert doc['files_scanned'] > 100
    # suppressions stay triaged: every baselined finding has a reasoned
    # baseline entry, every pragma carries a reason (else OCT000 would
    # have failed --check above)
    assert doc['baselined'] >= 1 and doc['pragmas'] >= 1


# -- racecheck ---------------------------------------------------------------

def test_racecheck_clean_consistent_order():
    rc = RaceCheck()
    a, b = rc.wrap('A'), rc.wrap('B')

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc.assert_clean()
    assert ('A', 'B') in rc.edges()


def test_racecheck_catches_inversion():
    """The reproducer: two threads acquire {A, B} in opposite orders.
    Neither run deadlocks (they execute sequentially), but the order
    graph has the cycle — racecheck flags the deadlock that a lucky
    interleaving hid."""
    rc = RaceCheck()
    a, b = rc.wrap('A'), rc.wrap('B')

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    for fn in (forward, backward):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    with pytest.raises(LockOrderInversion) as err:
        rc.check()
    msg = str(err.value)
    assert 'A -> B' in msg and 'B -> A' in msg


def test_racecheck_reports_distinct_cycles_over_same_locks():
    """A→B→C→A and A→C→B→A share a node set but are two separate
    inversions; both must appear in the diagnostic."""
    rc = RaceCheck(keep_stacks=False)
    for a, b in [('A', 'B'), ('B', 'C'), ('C', 'A'),
                 ('A', 'C'), ('C', 'B'), ('B', 'A')]:
        rc._edges[(a, b)] = {'count': 1, 'threads': {'t'},
                             'stack': None}
    cycles = {tuple(c) for c in rc.cycles()}
    assert ('A', 'B', 'C', 'A') in cycles
    assert ('A', 'C', 'B', 'A') in cycles


def test_racecheck_reentrant_is_not_an_edge():
    rc = RaceCheck()
    a = rc.wrap('A', threading.RLock())
    with a:
        with a:
            pass
    rc.assert_clean()
    assert rc.edges() == {}


def test_racecheck_instrument_in_place():
    class Obj:
        def __init__(self):
            self._lock = threading.Lock()

    obj = Obj()
    rc = RaceCheck()
    tracked = rc.instrument(obj, '_lock')
    assert obj._lock is tracked
    with obj._lock:
        pass
    # idempotent: instrumenting twice keeps the same proxy
    assert rc.instrument(obj, '_lock') is tracked
    # a NEW registry re-binds a foreign proxy so acquisitions report
    # to it, not silently to the old (dead) registry
    rc2 = RaceCheck()
    other = rc2.wrap('other')
    tracked2 = rc2.instrument(obj, '_lock')
    assert tracked2 is not tracked
    with other:
        with obj._lock:
            pass
    assert ('other', tracked2.name) in rc2.edges()
    assert rc.edges() == {}


def test_racecheck_engine_and_queue_locks_are_inversion_free(tmp_path):
    """Instrumented run of the real concurrency surface: the
    continuous engine's state/driver locks under a sweep drain with a
    mid-drain interactive submitter (the serve join path), plus the
    sweep queue's replay lock under concurrent enqueue/poll threads.
    Any lock-order inversion observed on ANY interleaving fails."""
    from opencompass_tpu.models import JaxLM
    from opencompass_tpu.serve.queue import SweepQueue

    rc = RaceCheck()
    lm = JaxLM(config='tiny', max_seq_len=256,
               continuous_batching=True, decode_slots=2,
               kv_page_size=16)
    engine = lm.continuous_engine()
    rc.instrument(engine, '_lock', 'engine._lock')
    rc.instrument(engine, '_driver', 'engine._driver')
    rc.instrument(lm, '_cont_engine_lock', 'model._cont_engine_lock')

    queue = SweepQueue(str(tmp_path / 'queue'))
    rc.instrument(queue, '_replay_lock', 'queue._replay_lock')

    got = {}

    def interactive():
        got['it'] = lm.generate_continuous(['interactive row'], 4)

    def poller():
        for i in range(5):
            queue.enqueue(config_path=f'/cfg/{i}.py', now=1000.0 + i)
            queue.pressure(now=1010.0)

    threads = [threading.Thread(target=interactive),
               threading.Thread(target=poller)]
    for t in threads:
        t.start()
    sweep = lm.generate_continuous(
        [f'sweep row {i} with words' for i in range(4)], 4)
    for t in threads:
        t.join()
    assert len(sweep) == 4 and len(got['it']) == 1
    assert rc.acquisitions > 0
    rc.assert_clean()


# -- crashfuzz ---------------------------------------------------------------

QUICK_CONTRACTS = sorted(crashfuzz.CONTRACTS)


@pytest.mark.parametrize('contract', QUICK_CONTRACTS)
def test_crashfuzz_quick_in_process(contract, tmp_path):
    """Every journal contract under randomized torn-write cuts (in-
    process writer: same bytes on disk as the killed child)."""
    report = crashfuzz.run_crashfuzz(contract, str(tmp_path),
                                     n_records=10, rounds=4, seed=7,
                                     in_process=True)
    assert report['rounds'] == 4     # violations raise AssertionError


def test_crashfuzz_child_process_queue(tmp_path):
    """One real killed-child round per sealing contract: the writer
    dies via os._exit mid-append at a byte offset, the reader and the
    surviving writer recover."""
    report = crashfuzz.run_crashfuzz('queue_journal', str(tmp_path),
                                     n_records=6, rounds=2, seed=3)
    assert report['rounds'] == 2


def test_crashfuzz_cut_at_zero_and_last_byte(tmp_path):
    """Deterministic corner cuts: nothing of the record landed, and
    torn one byte before the newline commit."""
    contract = crashfuzz.CONTRACTS['alerts']()
    for tag, cut_bytes_fn in (('zero', lambda line: 0),
                              ('last', lambda line: len(line) - 2)):
        root = tmp_path / tag
        path = str(root / contract.filename)
        os.makedirs(osp.dirname(path), exist_ok=True)
        records = [contract.make_record(i) for i in range(5)]
        line = json.dumps(records[3], separators=(',', ':')) + '\n'
        crashfuzz.torn_write(path, records, 3,
                             cut_bytes_fn(line.encode()))
        assert contract.read(path) == [f'slo-{i:04d}' for i in range(3)]
        contract.recover_append(path, records[3:])
        assert contract.read(path) == [f'slo-{i:04d}' for i in range(5)]


@pytest.mark.slow
@pytest.mark.parametrize('contract', QUICK_CONTRACTS)
def test_crashfuzz_full_child_sweep(contract, tmp_path):
    """The heavyweight tier: many randomized kill points per contract,
    each through a real child process, asserting bit-identical
    convergence after recovery."""
    report = crashfuzz.run_crashfuzz(contract, str(tmp_path),
                                     n_records=24, rounds=12, seed=0)
    assert report['rounds'] == 12


# -- clock injection (OCT005's satellite) ------------------------------------

def test_queue_timestamps_accept_injected_clock(tmp_path):
    from opencompass_tpu.serve.queue import SweepQueue
    q = SweepQueue(str(tmp_path))
    q.enqueue(config_path='/cfg/a.py', now=1000.0)
    q.enqueue(config_path='/cfg/b.py', now=1030.0)
    pressure = q.pressure(now=1100.0)
    assert pressure['oldest_queued_age_seconds'] == 100.0
    assert pressure['counts']['queued'] == 2


def test_top_snapshot_and_render_are_deterministic(tmp_path):
    """`cli top` snapshot/age math keyed entirely to the injected
    snapshot clock: two gathers with the same now= render the same
    frame, byte for byte."""
    from opencompass_tpu.serve import top
    from opencompass_tpu.serve.queue import SweepQueue

    cache_root = tmp_path / 'cache'
    queue_root = cache_root / 'serve' / 'queue'
    q = SweepQueue(str(queue_root))
    q.enqueue(config_path='/cfg/a.py', now=2000.0)
    frames = []
    for _ in range(2):
        snap = top.gather(str(cache_root), now=2060.0)
        assert snap['ts'] == 2060.0
        assert snap['serve']['queue_oldest_age_seconds'] == 60.0
        frames.append(top.render(snap))
    assert frames[0] == frames[1]
    assert 'oldest 60s' in frames[0]


def test_engine_info_accepts_injected_clock(tmp_path):
    from opencompass_tpu.obs import reqtrace
    reqtrace.write_engine_info(str(tmp_path), 8000, '/run', now=123.0)
    info = reqtrace.read_engine_info(str(tmp_path))
    assert info['ts'] == 123.0
