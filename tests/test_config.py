"""Config system: fromfile, read_base composition, dump round-trip, registry."""
import os

from opencompass_tpu.config import Config
from opencompass_tpu.registry import Registry


def _write(tmp_path, rel, content):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(content)
    return str(path)


def test_fromfile_basic(tmp_path):
    p = _write(tmp_path, 'a.py', "x = 1\nmodels = [dict(type='Fake', a=2)]\n")
    cfg = Config.fromfile(p)
    assert cfg.x == 1
    assert cfg.models[0].type == 'Fake'
    assert cfg.models[0].a == 2


def test_read_base_composition(tmp_path):
    _write(tmp_path, 'base/models.py', "models = [dict(type='M', n=1)]\n")
    p = _write(
        tmp_path, 'eval.py', 'from opencompass_tpu import read_base\n'
        'with read_base():\n'
        '    from .base.models import models\n'
        'work_dir = "out"\n')
    cfg = Config.fromfile(p)
    assert cfg.models[0].n == 1
    assert cfg.work_dir == 'out'


def test_read_base_parent_level(tmp_path):
    _write(tmp_path, 'datasets/mmlu.py', 'ds = [dict(abbr="mmlu")]\n')
    p = _write(
        tmp_path, 'runs/eval.py', 'from opencompass_tpu import read_base\n'
        'with read_base():\n'
        '    from ..datasets.mmlu import ds\n')
    cfg = Config.fromfile(p)
    assert cfg.ds[0].abbr == 'mmlu'


def test_dump_roundtrip(tmp_path):
    from opencompass_tpu.models import FakeModel
    p = _write(tmp_path, 'a.py', 'x = {"k": [1, 2, {"n": None}]}\n')
    cfg = Config.fromfile(p)
    cfg['models'] = [dict(type=FakeModel, path='fake')]
    out = str(tmp_path / 'dump.py')
    cfg.dump(out)
    cfg2 = Config.fromfile(out)
    assert cfg2.x == {'k': [1, 2, {'n': None}]}
    assert cfg2.models[0].type == 'opencompass_tpu.models.fake.FakeModel'


def test_registry_build_with_string_and_class():
    reg = Registry('test')

    @reg.register_module()
    class Foo:

        def __init__(self, v=0):
            self.v = v

    assert reg.build(dict(type='Foo', v=3)).v == 3
    assert reg.build(dict(type=Foo, v=4)).v == 4


def test_registry_dotted_path_fallback():
    reg = Registry('test2')
    obj = reg.build(dict(type='opencompass_tpu.models.fake.FakeModel',
                         path='fake'))
    assert obj.path == 'fake'


def test_merge_from_dict(tmp_path):
    p = _write(tmp_path, 'a.py', 'infer = dict(runner=dict(n=1))\n')
    cfg = Config.fromfile(p)
    cfg.merge_from_dict({'infer.runner.n': 8, 'new.key': 'v'})
    assert cfg.infer.runner.n == 8
    assert cfg.new.key == 'v'


def test_prompt_hash_stability():
    from opencompass_tpu.utils.prompt import get_prompt_hash
    cfg = dict(infer_cfg=dict(
        prompt_template=dict(type='PromptTemplate', template='{q}'),
        retriever=dict(type='ZeroRetriever'),
        inferencer=dict(type='GenInferencer')))
    h1 = get_prompt_hash(cfg)
    h2 = get_prompt_hash(dict(infer_cfg=dict(
        inferencer=dict(type='GenInferencer'),
        retriever=dict(type='ZeroRetriever'),
        prompt_template=dict(type='PromptTemplate', template='{q}'))))
    assert h1 == h2 and len(h1) == 64
    h3 = get_prompt_hash(dict(infer_cfg=dict(
        prompt_template=dict(type='PromptTemplate', template='{q} changed'),
        retriever=dict(type='ZeroRetriever'),
        inferencer=dict(type='GenInferencer'))))
    assert h3 != h1
