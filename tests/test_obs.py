"""Obs subsystem: span JSONL format, cross-process propagation, metrics,
no-op overhead, trace report, CLI smoke, and the FakeModel e2e run."""
import json
import os
import os.path as osp
import subprocess
import sys
import textwrap

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
FIXTURE_RUN = osp.join(REPO, 'tests', 'fixtures', 'obs_run')


@pytest.fixture(autouse=True)
def _isolated_tracer():
    """Each test starts and ends on the NoopTracer."""
    from opencompass_tpu import obs
    obs.reset_obs()
    yield
    obs.reset_obs()


def _read_events(work_dir):
    path = osp.join(work_dir, 'obs', 'events.jsonl')
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- span / event JSONL format --------------------------------------------

def test_span_jsonl_format_and_nesting(tmp_path):
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path))
    with tracer.span('outer', phase='infer') as outer:
        with tracer.span('inner') as inner:
            inner.set_attrs(rows=3)
        tracer.event('ping', detail='x')
    with pytest.raises(RuntimeError):
        with tracer.span('boom'):
            raise RuntimeError('kaput')
    tracer.close()

    events = _read_events(str(tmp_path))
    by_kind = {}
    for ev in events:
        by_kind.setdefault(ev['kind'], []).append(ev)
        # schema invariants on every line
        assert ev['v'] == 1
        assert isinstance(ev['ts'], float) and ev['ts'] > 0
        assert ev['trace'] == tracer.trace_id
        assert isinstance(ev['pid'], int)
    starts = {e['name']: e for e in by_kind['span_start']}
    ends = {e['name']: e for e in by_kind['span_end']}
    assert set(starts) == {'outer', 'inner', 'boom'}
    # in-process nesting via contextvars
    assert starts['inner']['parent'] == starts['outer']['span']
    assert 'parent' not in starts['outer']
    # attrs set mid-span ride on the end event
    assert ends['inner']['attrs']['rows'] == 3
    assert ends['outer']['attrs']['phase'] == 'infer'
    assert ends['outer']['dur'] >= ends['inner']['dur'] >= 0
    # error spans record status + exception
    assert ends['boom']['status'] == 'error'
    assert 'RuntimeError: kaput' in ends['boom']['error']
    assert ends['inner']['status'] == 'ok'
    # the ping event is attributed to the then-current span
    (ping,) = by_kind['event']
    assert ping['span'] == starts['outer']['span']
    assert ping['attrs'] == {'detail': 'x'}


def test_span_explicit_parent_for_pool_threads(tmp_path):
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path))
    with tracer.span('runner') as runner_span:
        pass
    with tracer.span('task', parent=runner_span):
        pass
    with tracer.span('orphan', parent=None):
        pass
    tracer.close()
    starts = {e['name']: e for e in _read_events(str(tmp_path))
              if e['kind'] == 'span_start'}
    assert starts['task']['parent'] == starts['runner']['span']
    assert 'parent' not in starts['orphan']


# -- cross-process propagation --------------------------------------------

def test_env_propagation_across_subprocess(tmp_path):
    """A real subprocess resumes the trace from OCT_* env vars and its
    spans parent under the launcher's span — the LocalRunner contract."""
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path))
    with tracer.span('task:demo') as span:
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   **tracer.propagation_env(span))
        child = textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            from opencompass_tpu import obs
            tracer = obs.init_task_obs({{'work_dir': 'unused'}})
            assert tracer.enabled
            with tracer.span('proc:child'):
                with tracer.span('inner:child'):
                    pass
            tracer.close()
        """)
        r = subprocess.run([sys.executable, '-c', child], env=env,
                           capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    tracer.close()

    events = _read_events(str(tmp_path))
    starts = {e['name']: e for e in events if e['kind'] == 'span_start'}
    parent_pid = starts['task:demo']['pid']
    child_root = starts['proc:child']
    # same trace, different process, parent = the launcher-side span
    assert child_root['trace'] == tracer.trace_id
    assert child_root['pid'] != parent_pid
    assert child_root['parent'] == starts['task:demo']['span']
    assert starts['inner:child']['parent'] == child_root['span']


def test_init_task_obs_disabled_without_env_or_cfg():
    from opencompass_tpu import obs
    for var in (obs.ENV_TRACE_ID, obs.ENV_PARENT_SPAN, obs.ENV_OBS_DIR):
        assert var not in os.environ
    tracer = obs.init_task_obs({'work_dir': 'unused'})
    assert not tracer.enabled


# -- metrics ----------------------------------------------------------------

def test_histogram_bucketing():
    from opencompass_tpu.obs import Histogram
    h = Histogram(buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.1, 0.5, 2.0, 99.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap['buckets'] == [0.1, 1.0, 10.0]
    # cumulative-upper-bound semantics: 0.05 and 0.1 land in <=0.1,
    # 0.5 in <=1.0, 2.0 in <=10.0, 99.0 overflows to +Inf
    assert snap['counts'] == [2, 1, 1, 1]
    assert snap['count'] == 5
    assert snap['sum'] == pytest.approx(101.65)


def test_metrics_registry_snapshot_and_flush(tmp_path):
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path))
    tracer.counter('c').inc()
    tracer.counter('c').inc(4)
    tracer.gauge('g').set(7)
    tracer.gauge('g').set(3)           # max tracks the high-water
    tracer.histogram('h').observe(0.2)
    tracer.close()                     # flushes the registry
    metrics = [e for e in _read_events(str(tmp_path))
               if e['kind'] == 'metrics']
    assert len(metrics) == 1
    attrs = metrics[0]['attrs']
    assert attrs['counters'] == {'c': 5}
    g = attrs['gauges']['g']
    assert (g['value'], g['max']) == (3, 7)
    assert g['ts'] > 0                 # last-set stamp drives staleness
    assert attrs['histograms']['h']['count'] == 1


# -- disabled path ----------------------------------------------------------

def test_noop_tracer_emits_nothing(tmp_path):
    """The enabled-off path: no obs/ dir, no events, metric and span calls
    are inert, and the hot-loop guard is a single False attribute."""
    from opencompass_tpu import obs
    tracer = obs.get_tracer()
    assert tracer.enabled is False
    with tracer.span('x', foo=1) as sp:
        sp.set_attrs(bar=2)
        tracer.event('nothing')
        tracer.counter('n').inc()
        tracer.gauge('n').set(1)
        tracer.histogram('n').observe(0.1)
    tracer.flush_metrics()
    tracer.close()
    assert tracer.propagation_env() == {}
    assert os.listdir(str(tmp_path)) == []


def test_init_obs_disabled_creates_no_dir(tmp_path):
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path), enabled=False)
    assert not tracer.enabled
    assert not osp.exists(osp.join(str(tmp_path), 'obs'))


# -- trace report (fixture, in-process) -------------------------------------

def test_build_report_from_fixture():
    from opencompass_tpu.obs.report import build_report
    rep = build_report(FIXTURE_RUN)
    assert rep['wall_seconds'] == pytest.approx(40.4)
    tasks = {t['name']: t for t in rep['tasks']}
    gen = tasks['OpenICLInfer[tiny/demo-gen]']
    # per-task wait/compile/device breakdown from the subprocess perf attrs
    assert gen['wait_seconds'] == 0.2
    assert gen['compile_seconds'] == 9.0
    assert gen['device_seconds'] == 12.5
    assert gen['steady_device_seconds'] == pytest.approx(3.5)
    assert gen['status'] == 'ok'
    ppl = tasks['OpenICLInfer[tiny/demo-ppl]']
    assert ppl['retries'] == 1 and ppl['status'] == 'error'
    # failure/retry summary counts the structured runner events
    assert rep['failures']['stall_timeout'] == 1
    assert rep['failures']['task_retry'] == 1
    assert rep['failures']['failed_tasks'] == 1
    # critical path descends run → phase → runner → gating task
    names = [h['name'] for h in rep['critical_path']]
    assert names[0] == 'run'
    assert names[-1] == 'task:OpenICLInfer[tiny/demo-ppl]'
    # slot utilization over the 2 declared host slots
    assert rep['slot_utilization']['num_slots'] == 2
    assert 0 < rep['slot_utilization']['overall'] <= 1
    # metrics merged across the two processes' flushes
    assert rep['metrics']['counters']['inferencer.gen_batches'] == 16
    assert rep['metrics']['counters']['runner.task_retries'] == 1
    assert rep['metrics']['histograms']['inferencer.batch_seconds'][
        'count'] == 16


def test_render_report_sections():
    from opencompass_tpu.obs.report import build_report, render_report
    text = render_report(build_report(FIXTURE_RUN))
    for needle in ('critical path', 'per-task breakdown', 'wait_s',
                   'compile_s', 'device_s', 'slot utilization',
                   'failures / retries', 'retries: 1', 'stall kills: 1'):
        assert needle in text, f'{needle!r} missing from report'


def test_build_report_resumed_run_uses_latest_trace(tmp_path):
    """A resumed run appends a second trace to the same events.jsonl;
    the report must not fold the idle gap / dead first attempt in."""
    obs_dir = tmp_path / 'obs'
    obs_dir.mkdir()
    lines = [
        # first attempt at t=1000, killed (no span_end)
        {'v': 1, 'kind': 'span_start', 'ts': 1000.0, 'trace': 'old1',
         'pid': 1, 'name': 'run', 'span': 's1'},
        # resume 5 h later under a fresh trace id
        {'v': 1, 'kind': 'span_start', 'ts': 19000.0, 'trace': 'new2',
         'pid': 2, 'name': 'run', 'span': 's2'},
        {'v': 1, 'kind': 'span_end', 'ts': 19010.0, 'trace': 'new2',
         'pid': 2, 'name': 'run', 'span': 's2', 'dur': 10.0,
         'status': 'ok'},
    ]
    with open(obs_dir / 'events.jsonl', 'w') as f:
        for rec in lines:
            f.write(json.dumps(rec) + '\n')
    from opencompass_tpu.obs.report import build_report
    rep = build_report(str(tmp_path))
    assert rep['trace'] == 'new2'
    assert rep['trace_ids'] == ['new2', 'old1']
    assert rep['wall_seconds'] == pytest.approx(10.0)  # not ~5 hours
    assert rep['n_spans'] == 1 and not rep['open_spans']
    # the first attempt stays reachable explicitly
    old = build_report(str(tmp_path), trace='old1')
    assert old['open_spans'] == ['run']


def test_histogram_quantile_overflow_bucket():
    from opencompass_tpu.obs.report import _histogram_quantile
    snap = {'buckets': [1.0, 10.0], 'counts': [1, 0, 3], 'sum': 100.0,
            'count': 4}
    assert _histogram_quantile(snap, 0.25) == 1.0
    # the 99th percentile lands in the +Inf overflow: render a marker,
    # never the string 'inf'
    assert _histogram_quantile(snap, 0.99) == '>10.0'
    assert _histogram_quantile({}, 0.5) is None


def test_resolve_events_path_variants(tmp_path):
    from opencompass_tpu.obs.report import resolve_events_path
    direct = osp.join(FIXTURE_RUN, 'obs', 'events.jsonl')
    assert resolve_events_path(FIXTURE_RUN) == direct
    assert resolve_events_path(osp.join(FIXTURE_RUN, 'obs')) == direct
    assert resolve_events_path(direct) == direct
    # parent dir holding timestamped run dirs → newest run with obs
    assert resolve_events_path(osp.dirname(FIXTURE_RUN)) is not None
    assert resolve_events_path(str(tmp_path)) is None


# -- CLI smoke (subprocess, no TPU) -----------------------------------------

def _cpu_env():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    return env


def test_trace_cli_smoke_on_fixture():
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'trace',
         'tests/fixtures/obs_run'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'per-task breakdown' in r.stdout
    assert 'OpenICLInfer[tiny/demo-gen]' in r.stdout
    assert 'compile_s' in r.stdout and 'wait_s' in r.stdout
    assert 'retries: 1' in r.stdout


def test_trace_cli_json_machine_readable():
    """`trace --json` emits the versioned report dict so CI can diff
    run trends (critical path + per-task breakdown)."""
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'trace',
         'tests/fixtures/obs_run', '--json'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep['v'] == 1
    names = {t['name'] for t in rep['tasks']}
    assert 'OpenICLInfer[tiny/demo-gen]' in names
    hops = [h['name'] for h in rep['critical_path']]
    assert hops and hops[0] == 'run'
    assert rep['failures']['task_retry'] == 1
    assert rep['metrics']['counters']['inferencer.gen_batches'] == 16


def test_trace_cli_missing_events_dir(tmp_path):
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'trace',
         str(tmp_path)],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 1
    assert 'events.jsonl' in r.stdout


# -- end-to-end FakeModel run ------------------------------------------------

def _find_http_port(work: str):
    """The run driver advertises its ephemeral --obs-port 0 port in
    {run_dir}/obs/http.json."""
    for sub in os.listdir(work):
        cand = osp.join(work, sub, 'obs', 'http.json')
        if osp.isfile(cand):
            try:
                with open(cand) as f:
                    return json.load(f).get('port')
            except (OSError, ValueError):
                pass   # torn write: retry next poll
    return None


@pytest.fixture(scope='module')
def obs_e2e_run(tmp_path_factory):
    """One full `run.py --obs --obs-port 0` pipeline (LocalRunner
    subprocesses, CPU) shared by the e2e assertions below.  The driver
    runs under Popen so the live /metrics, /status, and /healthz
    endpoints can be scraped mid-run."""
    import time
    import urllib.request
    work = str(tmp_path_factory.mktemp('obs_e2e'))
    out_path = osp.join(str(tmp_path_factory.mktemp('obs_e2e_log')),
                        'driver.out')
    live = {}
    with open(out_path, 'w') as out_f:
        proc = subprocess.Popen(
            [sys.executable, 'run.py', 'configs/eval_demo.py', '-w', work,
             '--obs', '--obs-port', '0', '--max-num-workers', '2'],
            cwd=REPO, env=_cpu_env(), stdout=out_f,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 420
        try:
            while time.time() < deadline and proc.poll() is None:
                port = _find_http_port(work)
                if port:
                    base = f'http://127.0.0.1:{port}'
                    try:
                        metrics = urllib.request.urlopen(
                            base + '/metrics', timeout=5).read().decode()
                        # keep scraping until the aggregated task gauges
                        # show up (the first seconds of a run have no
                        # tasks registered yet)
                        if 'oct_run_progress' in metrics:
                            live['metrics'] = metrics
                            live['healthz'] = urllib.request.urlopen(
                                base + '/healthz',
                                timeout=5).read().decode()
                            live['status'] = json.loads(
                                urllib.request.urlopen(
                                    base + '/status',
                                    timeout=5).read().decode())
                            break
                    except OSError:
                        pass   # server mid-start/stop: retry
                time.sleep(0.2)
            proc.wait(timeout=max(1.0, deadline - time.time()))
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    with open(out_path) as f:
        out = f.read()
    assert proc.returncode == 0, out
    # the work root holds the timestamped run dir plus the sweep-shared
    # cache/ (compile cache + result store)
    (run_dir,) = [d for d in os.listdir(work) if d != 'cache']
    return {'run_dir': osp.join(work, run_dir), 'stdout': out,
            'live': live}


def test_e2e_obs_events_and_nesting(obs_e2e_run):
    run_dir = obs_e2e_run['run_dir']
    events = _read_events(run_dir)
    starts = {e['span']: e for e in events if e['kind'] == 'span_start'}
    by_name = {}
    for e in starts.values():
        by_name.setdefault(e['name'].split(':')[0], []).append(e)
    # runner → task → proc → infer/eval nesting, across processes
    assert by_name.get('run') and by_name.get('runner') \
        and by_name.get('task') and by_name.get('proc')
    for proc in by_name['proc']:
        parent = starts[proc['parent']]
        assert parent['name'].startswith('task:')
        assert proc['pid'] != parent['pid']  # real process boundary
    for leaf_kind in ('infer', 'eval'):
        for leaf in by_name[leaf_kind]:
            assert starts[leaf['parent']]['name'].startswith('proc:')
    # infer spans carry the TaskProfiler perf record (compile/device split)
    infer_ends = [e for e in events if e['kind'] == 'span_end'
                  and e['name'].startswith('infer:')]
    assert infer_ends
    for e in infer_ends:
        perf = e['attrs']['perf']
        assert 'device_seconds' in perf and 'compile_seconds' in perf


def test_e2e_trace_report_renders(obs_e2e_run):
    run_dir = obs_e2e_run['run_dir']
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'trace', run_dir],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'per-task breakdown' in r.stdout
    assert 'wait_s' in r.stdout and 'compile_s' in r.stdout \
        and 'device_s' in r.stdout
    assert 'failures / retries' in r.stdout
    assert 'OpenICLInfer' in r.stdout and 'OpenICLEval' in r.stdout


def test_e2e_summarizer_obs_section(obs_e2e_run):
    run_dir = obs_e2e_run['run_dir']
    assert '\nobs:\n' in obs_e2e_run['stdout']
    (summary,) = [f for f in os.listdir(osp.join(run_dir, 'summary'))
                  if f.endswith('.txt')]
    text = open(osp.join(run_dir, 'summary', summary)).read()
    assert 'obs format' in text
    assert 'tasks' in text and 'retries' in text
    # driver log file handler (logging satellite)
    assert osp.exists(osp.join(run_dir, 'logs', 'driver.log'))


# -- live telemetry plane (scraped mid-run by the fixture) -------------------

def test_e2e_live_metrics_endpoint(obs_e2e_run):
    """--obs-port 0 exposes /metrics (valid Prometheus text format),
    /status (JSON snapshot), and /healthz while the run is live."""
    import re
    live = obs_e2e_run['live']
    assert live, 'live endpoints were never scraped during the run'
    assert live['healthz'].strip() == 'ok'
    metrics = live['metrics']
    assert '# TYPE oct_run_progress gauge' in metrics
    assert 'oct_run_progress' in metrics
    # every line is comment-or-sample per text format 0.0.4
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+$')
    for line in metrics.strip().splitlines():
        if line.startswith('#'):
            assert re.match(r'^# (TYPE|HELP) ', line), line
        else:
            assert sample.match(line), line
    status = live['status']
    assert status['v'] == 1
    assert status['state'] in ('running', 'done')
    assert isinstance(status['tasks'], dict)
    assert status['overall']['n_tasks'] >= 1


def test_e2e_status_json_converges(obs_e2e_run):
    """The aggregator's final snapshot reports a fully-complete run,
    and every task heartbeat reached a terminal state."""
    run_dir = obs_e2e_run['run_dir']
    with open(osp.join(run_dir, 'obs', 'status.json')) as f:
        snap = json.load(f)
    assert snap['v'] == 1 and snap['state'] == 'done'
    assert snap['overall']['progress'] == 1.0
    assert snap['overall']['failed'] == 0
    assert snap['overall']['ok'] == snap['overall']['n_tasks'] >= 1
    progress_dir = osp.join(run_dir, 'obs', 'progress')
    heartbeats = [f for f in os.listdir(progress_dir)
                  if f.endswith('.json')]
    assert heartbeats, 'no task heartbeat files were written'
    for fname in heartbeats:
        with open(osp.join(progress_dir, fname)) as f:
            rec = json.load(f)
        assert rec['v'] == 1 and rec['state'] == 'done'
        if rec.get('units_total'):
            assert rec['units_done'] == rec['units_total']
    # a dead run must not advertise a stale endpoint
    assert not osp.exists(osp.join(run_dir, 'obs', 'http.json'))


def test_e2e_status_cli_on_finished_run(obs_e2e_run):
    """`cli status` works purely from files after the run has exited."""
    run_dir = obs_e2e_run['run_dir']
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'status', run_dir],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'state: done' in r.stdout
    assert '100%' in r.stdout
    assert 'OpenICLEval' in r.stdout


def test_e2e_flight_recorder_and_ledger(obs_e2e_run):
    """Tier-1 wiring check for the flight-recorder layer: the
    subprocess sweep wrote per-batch timelines, one ledger record per
    (model, dataset) landed under the sweep cache root with inferencer-
    kind attribution, and the CI perf-gate invocation (`cli ledger
    check --trajectory`) runs clean on the repo's bench trajectory."""
    run_dir = obs_e2e_run['run_dir']
    from opencompass_tpu.obs.timeline import summarize_timelines
    summaries = summarize_timelines(osp.join(run_dir, 'obs'))
    assert summaries, 'no per-batch timeline files were written'
    assert sum(s['batches'] for s in summaries.values()) >= 2
    # trace report grew the flight-recorder section
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'trace', run_dir],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'flight recorder' in r.stdout
    # one ledger record per (model, dataset), kind-attributed
    led = osp.join(osp.dirname(run_dir), 'cache', 'ledger')
    from opencompass_tpu.ledger import iter_ledger
    records = list(iter_ledger(osp.join(led, 'runs.jsonl')))
    assert records, 'driver appended no ledger records'
    assert all(rec['run'] == osp.basename(run_dir) for rec in records)
    assert {'gen', 'ppl'} <= {rec['kind'] for rec in records}
    # CI perf gate: exits 0 here, non-zero on a thresholded regression
    # (tests/test_flight_recorder.py proves the failing side)
    # generous threshold: the committed bench trajectory carries real
    # machine-to-machine noise (this gate exercises the wiring and
    # catches order-of-magnitude regressions, not 25% jitter)
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'ledger', 'check',
         '--ledger', led, '--trajectory', 'BENCH_TRAJECTORY.json',
         '--max-slowdown', '0.9'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_obs_unset_creates_no_obs_dir(tmp_path):
    """Default runs must not grow an obs/ directory (zero-overhead-off)."""
    work = str(tmp_path / 'out')
    r = subprocess.run(
        [sys.executable, 'run.py', 'configs/eval_demo.py', '-w', work,
         '--debug'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr
    (run_dir,) = [d for d in os.listdir(work) if d != 'cache']
    assert not osp.exists(osp.join(work, run_dir, 'obs'))
