"""Golden-file summarizer parity: the summary table must keep the
reference's column set and file layout (reference utils/summarizer.py:
157-233 — dataset/version/metric/mode + one column per model; txt with
time stamp and tabulate/csv/raw sections fenced by ^...$; csv identical
to the table).  The fixture under tests/fixtures pins the exact csv
bytes so format drift fails loudly."""
import os.path as osp

from tests.test_orchestration import _demo_cfg

FIXTURE = osp.join(osp.dirname(__file__), 'fixtures',
                   'summary_golden.csv')


def _summarize_two_models(tmp_path):
    from opencompass_tpu.utils.summarizer import Summarizer
    cfg = _demo_cfg(tmp_path)
    base_model = dict(cfg['models'][0])
    model_a = dict(base_model, abbr='model-a')
    model_b = dict(base_model, abbr='model-b')
    cfg['models'] = [model_a, model_b]
    cfg['summarizer'] = {
        'summary_groups': [
            {'name': 'demo-avg', 'subsets': ['demo-gen', 'demo-ppl']},
            {'name': 'demo-weighted',
             'subsets': ['demo-gen', 'demo-ppl'],
             'weights': {'demo-gen': 3, 'demo-ppl': 1}},
        ]
    }
    for abbr, scores in [('model-a', {'demo-gen': '{"score": 80.0}',
                                      'demo-ppl': '{"accuracy": 40.0}'}),
                         ('model-b', {'demo-gen': '{"score": 50.0}'})]:
        res_dir = tmp_path / 'results' / abbr
        res_dir.mkdir(parents=True)
        for d_abbr, payload in scores.items():
            (res_dir / f'{d_abbr}.json').write_text(payload)
    Summarizer(cfg).summarize('golden')
    out = tmp_path / 'summary'
    return ((out / 'summary_golden.txt').read_text(),
            (out / 'summary_golden.csv').read_text())


def test_csv_matches_golden_fixture(tmp_path):
    _, csv_text = _summarize_two_models(tmp_path)
    assert csv_text == open(FIXTURE).read()


def test_csv_columns_and_group_metrics(tmp_path):
    _, csv_text = _summarize_two_models(tmp_path)
    rows = [line.split(',') for line in csv_text.strip().splitlines()]
    assert rows[0] == ['dataset', 'version', 'metric', 'mode',
                      'model-a', 'model-b']
    by_dataset = {r[0]: r for r in rows[1:]}
    # per-dataset rows: metric + mode + '{:.02f}' scores, '-' when absent
    assert by_dataset['demo-gen'][2:] == ['score', 'gen', '80.00', '50.00']
    assert by_dataset['demo-ppl'][2] == 'accuracy'
    assert by_dataset['demo-ppl'][4:] == ['40.00', '-']
    # group rows: naive + weighted averages with the reference metric names
    assert by_dataset['demo-avg'][2] == 'naive_average'
    assert by_dataset['demo-avg'][4] == '60.00'
    assert by_dataset['demo-weighted'][2] == 'weighted_average'
    assert by_dataset['demo-weighted'][4] == '70.00'
    # model-b is missing demo-ppl, so its groups cannot aggregate
    assert by_dataset['demo-avg'][5] == '-'
    # version column is a 6-char prompt hash
    assert len(by_dataset['demo-gen'][1]) == 6


def test_txt_sections_match_reference_layout(tmp_path):
    txt, csv_text = _summarize_two_models(tmp_path)
    lines = txt.splitlines()
    assert lines[0] == 'golden'                 # time_str stamp
    assert lines[1] == 'tabulate format'
    assert lines[2] == '^' * 128
    for section in ('csv format', 'raw format'):
        assert section in lines
    assert 'THIS IS A DIVIDER' in txt
    # the csv section reproduces the csv file byte for byte
    start = lines.index('csv format') + 2
    end = lines.index('$' * 128, start)
    assert '\n'.join(lines[start:end]) + '\n' == csv_text
    # raw section lists every model with its raw result dicts
    assert 'Model: model-a' in txt and 'Model: model-b' in txt
    assert "{'score': 80.0}" in txt
