"""nn/ stack: forward, cached decode, sharding equivalence, architectures."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.nn import (TransformerConfig, forward, greedy_generate,
                                init_params, sequence_nll, shard_params)
from opencompass_tpu.parallel import MeshSpec, make_mesh, use_mesh


@pytest.fixture(scope='module')
def tiny():
    cfg = TransformerConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_forward_shapes_and_dtype(tiny):
    cfg, params = tiny
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                              cfg.vocab_size)
    logits = forward(params, cfg, toks)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_pad_mask_right_does_not_change_prefix_logits(tiny):
    cfg, params = tiny
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                              cfg.vocab_size)
    full = forward(params, cfg, toks)
    padded = jnp.concatenate(
        [toks, jnp.zeros((1, 4), toks.dtype)], axis=1)
    mask = jnp.concatenate(
        [jnp.ones((1, 8), bool), jnp.zeros((1, 4), bool)], axis=1)
    out = forward(params, cfg, padded, mask)
    np.testing.assert_allclose(np.asarray(out[:, :8]), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_decode_matches_teacher_forcing(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                                cfg.vocab_size)
    pmask = jnp.ones((2, 8), bool)
    out, _ = greedy_generate(params, cfg, prompt, pmask, 6)
    full = jnp.concatenate([prompt, out], axis=1)
    ref = jnp.argmax(forward(params, cfg, full), axis=-1)
    for i in range(6):
        assert bool(jnp.all(ref[:, 7 + i] == out[:, i])), f'step {i}'


def test_decode_left_padding_invariance(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                cfg.vocab_size)
    pmask = jnp.ones((2, 8), bool)
    out1, _ = greedy_generate(params, cfg, prompt, pmask, 5)
    padded = jnp.concatenate(
        [jnp.zeros((2, 3), prompt.dtype), prompt], axis=1)
    padmask = jnp.concatenate([jnp.zeros((2, 3), bool), pmask], axis=1)
    out2, _ = greedy_generate(params, cfg, padded, padmask, 5)
    assert bool(jnp.all(out1 == out2))


def test_eos_early_stop_pads_output(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0,
                                cfg.vocab_size)
    pmask = jnp.ones((1, 8), bool)
    base, _ = greedy_generate(params, cfg, prompt, pmask, 8)
    eos = int(base[0, 2])  # pretend the 3rd emitted token is EOS
    out, lengths = greedy_generate(params, cfg, prompt, pmask, 8,
                                   eos_token_id=eos, pad_token_id=0)
    n = int(lengths[0])
    assert n <= 3 or eos not in base[0, :3]
    assert bool(jnp.all(out[0, n:] == 0))


def test_sequence_nll_mask_length_excludes_context(tiny):
    cfg, params = tiny
    toks = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0,
                              cfg.vocab_size)
    mask = jnp.ones((2, 12), bool)
    logits = forward(params, cfg, toks, mask)
    full = sequence_nll(logits, toks, mask)
    masked = sequence_nll(logits, toks, mask,
                          mask_length=jnp.asarray([6, 6]))
    assert full.shape == (2,)
    assert not np.allclose(np.asarray(full), np.asarray(masked))


def test_tensor_parallel_matches_single_device(tiny):
    cfg, params = tiny
    toks = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0,
                              cfg.vocab_size)
    ref = forward(params, cfg, toks)
    mesh = make_mesh(MeshSpec(data=2, model=2, seq=1))
    with use_mesh(mesh):
        sp = shard_params(params, cfg, mesh)
        out = jax.jit(lambda p, t: forward(p, cfg, t))(sp, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_tensor_parallel_decode_matches(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0,
                                cfg.vocab_size)
    pmask = jnp.ones((2, 8), bool)
    ref, _ = greedy_generate(params, cfg, prompt, pmask, 4)
    mesh = make_mesh(MeshSpec(data=2, model=2, seq=1))
    with use_mesh(mesh):
        sp = shard_params(params, cfg, mesh)
        out, _ = jax.jit(
            lambda p, t, m: greedy_generate(p, cfg, t, m, 4))(sp, prompt,
                                                              pmask)
    assert bool(jnp.all(out == ref))


@pytest.mark.parametrize('family_kw', [
    dict(norm='layernorm', positional='learned', gated_mlp=False,
         activation='relu', qkv_bias=True, o_bias=True, mlp_bias=True,
         tie_embeddings=True, pos_offset=2),           # OPT-style
    dict(parallel_residual=True, norm='layernorm', gated_mlp=False,
         activation='gelu', num_kv_heads=1),           # Falcon-style MQA
    dict(qkv_bias=True, num_kv_heads=2),               # Qwen2-style GQA
    dict(positional='alibi'),                          # Baichuan-13B style
    dict(positional='alibi', norm='layernorm', embed_norm=True,
         gated_mlp=False, activation='gelu_new', qkv_bias=True,
         o_bias=True, mlp_bias=True, tie_embeddings=True,
         num_kv_heads=4),                              # BLOOM style (MHA)
])
def test_architecture_variants_run(family_kw):
    cfg = TransformerConfig.tiny(**family_kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                              cfg.vocab_size)
    logits = forward(params, cfg, toks)
    assert logits.shape == (2, 8, cfg.vocab_size)
    out, _ = greedy_generate(params, cfg, toks, jnp.ones((2, 8), bool), 3)
    assert out.shape == (2, 3)


def test_alibi_decode_matches_teacher_forcing():
    """ALiBi bias must agree between the full forward and the cached
    decode path (per-slot kv positions)."""
    cfg = TransformerConfig.tiny(positional='alibi')
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                                cfg.vocab_size)
    pmask = jnp.ones((2, 8), bool)
    out, _ = greedy_generate(params, cfg, prompt, pmask, 6)
    full = jnp.concatenate([prompt, out], axis=1)
    ref = jnp.argmax(forward(params, cfg, full), axis=-1)
    for i in range(6):
        assert bool(jnp.all(ref[:, 7 + i] == out[:, i])), f'step {i}'
    # left-padding invariance: slot index != position, bias must follow
    # positions, not slots
    padded = jnp.concatenate(
        [jnp.zeros((2, 3), prompt.dtype), prompt], axis=1)
    padmask = jnp.concatenate([jnp.zeros((2, 3), bool), pmask], axis=1)
    out2, _ = greedy_generate(params, cfg, padded, padmask, 6)
    assert bool(jnp.all(out == out2))


def test_alibi_bias_applied_and_shaped():
    """The bias actually reaches the scores (zeroing it changes logits)
    and follows the paper's slope/distance form."""
    from unittest import mock

    from opencompass_tpu.nn import transformer as T

    slopes = np.asarray(T._alibi_slopes(8))
    assert slopes.shape == (8,)
    assert np.all(np.diff(slopes) < 0) and slopes[0] == 0.5
    q_pos = jnp.asarray([[2, 3]])
    kv_pos = jnp.asarray([[0, 1, 2, 3]])
    bias = np.asarray(T._alibi_bias(
        TransformerConfig.tiny(positional='alibi'), q_pos, kv_pos))
    # head 0 slope for 4 heads is 2^-2; distance 2 → bias -1.0
    assert bias[0, 0, 0, 0] == pytest.approx(-0.25 * 2)

    cfg = TransformerConfig.tiny(positional='alibi')
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0,
                              cfg.vocab_size)
    with_bias = np.asarray(forward(params, cfg, toks))
    with mock.patch.object(T, '_alibi_bias',
                           lambda *a: jnp.zeros((1, cfg.num_heads, 8, 8))):
        without = np.asarray(forward(params, cfg, toks))
    assert not np.allclose(with_bias, without)


def test_baichuan_13b_maps_to_alibi():
    hf = dict(model_type='baichuan', vocab_size=64000, hidden_size=5120,
              num_hidden_layers=40, num_attention_heads=40,
              intermediate_size=13696, max_position_embeddings=4096)
    cfg = TransformerConfig.from_hf_config(hf)
    assert cfg.positional == 'alibi'
    hf7b = dict(hf, hidden_size=4096, num_hidden_layers=32,
                num_attention_heads=32, intermediate_size=11008)
    assert TransformerConfig.from_hf_config(hf7b).positional == 'rope'


def test_scan_vs_unrolled_layers_match(tiny):
    cfg, params = tiny
    import dataclasses
    cfg2 = dataclasses.replace(cfg, scan_layers=False)
    toks = jax.random.randint(jax.random.PRNGKey(8), (1, 8), 0,
                              cfg.vocab_size)
    a = forward(params, cfg, toks)
    b = forward(params, cfg2, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-5)
