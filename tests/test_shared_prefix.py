"""Shared-prefix prefill reuse (transformer.forward_shared for
scoring, prefill_suffix for generation).

The eval workload's prompts share long prefixes — FixKRetriever 5-shot
ICE blocks are identical across a subset's items, and a PPL item's
label variants differ only in the answer.  These tests pin the
optimization's contract: scoring and generation over
``concat(prefix, row)`` computed via one batch-1 prefix prefill +
per-row suffixes must match the plain full-prompt paths numerically.
No reference counterpart (the reference re-runs every full prompt:
reference opencompass/models/huggingface.py:127-293).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.nn import (TransformerConfig, forward,
                                greedy_generate, greedy_generate_prefixed,
                                init_params, sequence_nll,
                                shared_prefix_nll)

CFG = TransformerConfig.tiny()
V = CFG.vocab_size


def _rows(B=3, P=10, S=6, seed=0):
    rng = np.random.RandomState(seed)
    prefix = jnp.asarray(rng.randint(0, V, (P,)), jnp.int32)
    # ragged suffixes, right-padded for scoring
    lens = [S, S - 2, S - 4][:B]
    toks = np.zeros((B, S), np.int32)
    mask = np.zeros((B, S), bool)
    for i, L in enumerate(lens):
        toks[i, :L] = rng.randint(0, V, (L,))
        mask[i, :L] = True
    return prefix, jnp.asarray(toks), jnp.asarray(mask), lens


def _concat(prefix, toks, mask, lens):
    """Plain-path equivalents: full prompts, right-padded."""
    P = prefix.shape[0]
    B, S = toks.shape
    full = np.zeros((B, P + S), np.int32)
    fmask = np.zeros((B, P + S), bool)
    for i, L in enumerate(lens):
        full[i, :P] = np.asarray(prefix)
        full[i, P:P + L] = np.asarray(toks)[i, :L]
        fmask[i, :P + L] = True
    return jnp.asarray(full), jnp.asarray(fmask)


def test_shared_prefix_nll_matches_plain():
    params = init_params(CFG, jax.random.PRNGKey(0))
    prefix, toks, mask, lens = _rows()
    full, fmask = _concat(prefix, toks, mask, lens)
    want = np.asarray(sequence_nll(
        forward(params, CFG, full, fmask, use_flash=False), full, fmask))
    got = np.asarray(shared_prefix_nll(params, CFG, prefix, toks, mask))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_shared_prefix_nll_mask_length_matches_plain():
    params = init_params(CFG, jax.random.PRNGKey(1))
    prefix, toks, mask, lens = _rows(seed=3)
    full, fmask = _concat(prefix, toks, mask, lens)
    P = prefix.shape[0]
    # context exclusion at, below, and above the prefix boundary
    ml = jnp.asarray([P, P - 3, P + 2], jnp.int32)
    want = np.asarray(sequence_nll(
        forward(params, CFG, full, fmask, use_flash=False), full, fmask,
        mask_length=ml))
    got = np.asarray(shared_prefix_nll(params, CFG, prefix, toks, mask,
                                       mask_length=ml))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_shared_prefix_nll_kv_quant_config_unaffected():
    """A w8a8-kv4 model's SCORING must be identical through the shared
    path: the decode-only KV quantization may not leak into it."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    cfgq = dataclasses.replace(CFG, kv_quant='int4')
    prefix, toks, mask, lens = _rows(seed=5)
    a = np.asarray(shared_prefix_nll(params, CFG, prefix, toks, mask))
    b = np.asarray(shared_prefix_nll(params, cfgq, prefix, toks, mask))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_prefixed_generate_matches_plain():
    """Left-padded remainders behind a shared prefix must reproduce the
    plain generator's tokens (greedy chain equality on the CPU mesh)."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    rng = np.random.RandomState(7)
    P, B, S = 12, 3, 5
    prefix = jnp.asarray(rng.randint(0, V, (P,)), jnp.int32)
    lens = [S, S - 1, S - 3]
    toks = np.zeros((B, S), np.int32)
    mask = np.zeros((B, S), bool)
    for i, L in enumerate(lens):           # LEFT-padded for generation
        toks[i, S - L:] = rng.randint(0, V, (L,))
        mask[i, S - L:] = True
    toks, mask = jnp.asarray(toks), jnp.asarray(mask)

    fullB = np.zeros((B, P + S), np.int32)
    fmask = np.zeros((B, P + S), bool)
    for i, L in enumerate(lens):           # left-padded full prompts
        fullB[i, S - L:S - L + P] = np.asarray(prefix)
        fullB[i, S - L + P:] = np.asarray(toks)[i, S - L:]
        fmask[i, S - L:] = True
    out_plain, len_plain = jax.jit(lambda p, t, m: greedy_generate(
        p, CFG, t, m, 8, eos_token_id=None))(params, jnp.asarray(fullB),
                                             jnp.asarray(fmask))
    out_pre, len_pre = jax.jit(lambda p, pre, t, m: greedy_generate_prefixed(
        p, CFG, pre, t, m, 8, eos_token_id=None))(params, prefix, toks,
                                                  mask)
    np.testing.assert_array_equal(np.asarray(out_plain),
                                  np.asarray(out_pre))
    np.testing.assert_array_equal(np.asarray(len_plain),
                                  np.asarray(len_pre))


def test_prefixed_generate_eos_and_quant():
    """Composes with the serving quantization and EOS handling."""
    from opencompass_tpu.nn.quant import quantize_params
    cfgq = dataclasses.replace(CFG, act_quant=True, kv_quant='int4')
    params = quantize_params(init_params(CFG, jax.random.PRNGKey(0)), CFG)
    rng = np.random.RandomState(11)
    prefix = jnp.asarray(rng.randint(0, V, (8,)), jnp.int32)
    toks = jnp.asarray(rng.randint(0, V, (2, 4)), jnp.int32)
    mask = jnp.ones((2, 4), bool)
    out, lengths = jax.jit(lambda p, pre, t, m: greedy_generate_prefixed(
        p, cfgq, pre, t, m, 6, eos_token_id=5))(params, prefix, toks,
                                                mask)
    assert out.shape == (2, 6)
    out = np.asarray(out)
    for i in range(2):
        if (out[i] == 5).any():
            first = int(np.argmax(out[i] == 5))
            assert (out[i, first + 1:] == 0).all()


def _mk_lms():
    from opencompass_tpu.models import JaxLM
    kw = dict(config='tiny', max_seq_len=512, dtype='float32')
    return (JaxLM(shared_prefix=True, **kw),
            JaxLM(shared_prefix=False, **kw))


def test_jaxlm_ppl_shared_matches_plain():
    lm_on, lm_off = _mk_lms()
    base = ('Passage: the quick brown fox jumps over the lazy dog and '
            'then continues running through the long field for a while '
            'before finally stopping near the river to rest. ') * 4 \
        + 'Question: '
    texts = [base + q for q in
             ('what is A?', 'what is B maybe?', 'what is C exactly now?')]
    # confirm the shared path actually engages (byte tokenizer: the
    # prefix exceeds the 256-token engagement quantum)
    ids = [lm_on._encode_ids(t) for t in texts]
    pre, _ = lm_on._shared_prefix_split(ids)
    assert pre is not None and len(pre) >= 256
    a = lm_on.get_ppl(texts)
    b = lm_off.get_ppl(texts)
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3)


def test_jaxlm_ppl_shared_mask_length_matches_plain():
    lm_on, lm_off = _mk_lms()
    base = 'x' * 300 + ' answer choice: '
    texts = [base + c for c in ('alpha', 'beta', 'gamma gamma')]
    ml = [len(lm_on._encode_ids(base))] * 3
    a = lm_on.get_ppl(texts, mask_length=ml)
    b = lm_off.get_ppl(texts, mask_length=ml)
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3)


def test_jaxlm_generate_shared_matches_plain():
    lm_on, lm_off = _mk_lms()
    base = ('Example 1: in goes one, out comes two. Example 2: in goes '
            'two, out comes three. Example 3: in goes nine, out comes '
            'ten. ') * 3 + 'Now the question is about the number '
    texts = [base + q for q in ('four.', 'seventeen!', 'zero?')]
    a = lm_on.generate(texts, max_out_len=8)
    b = lm_off.generate(texts, max_out_len=8)
    assert a == b


def test_jaxlm_short_prompts_skip_shared_path():
    lm_on, _ = _mk_lms()
    ids = [lm_on._encode_ids(t) for t in ('short a', 'short b')]
    pre, rows = lm_on._shared_prefix_split(ids)
    assert pre is None and rows == ids
    out = lm_on.get_ppl(['short a', 'short b'])
    assert all(np.isfinite(out))


def test_prefixed_generate_alibi_raises():
    cfg = dataclasses.replace(CFG, positional='alibi')
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(NotImplementedError):
        greedy_generate_prefixed(params, cfg,
                                 jnp.zeros((4,), jnp.int32),
                                 jnp.zeros((1, 2), jnp.int32),
                                 jnp.ones((1, 2), bool), 4)


def test_shared_nll_guards_unsupported_configs():
    """ALiBi / prefix-LM must refuse loudly, not return wrong NLLs."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    args = (jnp.zeros((4,), jnp.int32), jnp.zeros((1, 2), jnp.int32),
            jnp.ones((1, 2), bool))
    for bad in (dataclasses.replace(CFG, positional='alibi'),
                dataclasses.replace(CFG, prefix_lm=True)):
        with pytest.raises(NotImplementedError):
            shared_prefix_nll(params, bad, *args)


def test_prefixed_generate_filler_rows_done_immediately():
    """All-pad suffix rows are batch-bucket filler: they emit pads and
    count as done, so they can't defeat the all-done early exit."""
    params = init_params(CFG, jax.random.PRNGKey(2))
    prefix = jnp.asarray(np.random.RandomState(1).randint(0, V, (8,)),
                         jnp.int32)
    toks = jnp.zeros((2, 3), jnp.int32)
    mask = jnp.zeros((2, 3), bool)
    mask = mask.at[0].set(True)            # row 1 is filler
    toks = toks.at[0].set(jnp.asarray([1, 2, 3]))
    out, lengths = jax.jit(lambda p, pre, t, m: greedy_generate_prefixed(
        p, CFG, pre, t, m, 5, eos_token_id=None, pad_token_id=0))(
            params, prefix, toks, mask)
    out = np.asarray(out)
    assert (out[1] == 0).all()             # filler emitted only pads
