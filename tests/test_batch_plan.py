"""Length-aware batch planner (icl/inferencers/schedule.py): packing
invariants, plan-vs-sequential prediction equivalence on FakeModel and a
tiny JaxLM, out-of-order resume, and the flush-condition fix."""
import json

import pytest
from datasets import Dataset, DatasetDict

from opencompass_tpu.datasets.base import BaseDataset
from opencompass_tpu.icl.inferencers import (CLPInferencer, GenInferencer,
                                             PPLInferencer)
from opencompass_tpu.icl.inferencers import schedule
from opencompass_tpu.icl.prompt_template import PromptTemplate
from opencompass_tpu.icl.retrievers import ZeroRetriever
from opencompass_tpu.models import FakeModel


def pow2_shape(n_rows, longest):
    """A JaxLM-style power-of-two bucketing shape fn."""
    from opencompass_tpu.models.jax_lm import _bucket
    return _bucket(max(n_rows, 1), lo=1), _bucket(max(longest, 1))


# ---------------------------------------------------------------------------
# planner unit tests
# ---------------------------------------------------------------------------

def test_plan_covers_every_row_once():
    lengths = [5, 300, 12, 2000, 40, 7, 950, 31]
    plan = schedule.plan_batches(lengths, batch_size=3)
    seen = sorted(i for b in plan for i in b.indices)
    assert seen == list(range(len(lengths)))


def test_plan_respects_batch_size_and_budget():
    lengths = [100] * 10 + [2000] * 4
    plan = schedule.plan_batches(lengths, batch_size=8,
                                 shape_fn=pow2_shape, token_budget=4096)
    for b in plan:
        assert len(b.indices) <= 8
        assert b.padded_tokens <= 4096 or len(b.indices) == 1
    # long rows must not share a batch with short ones under this budget:
    # a 2048-bucket row allows at most 2 rows per batch
    for b in plan:
        if b.longest >= 2000:
            assert len(b.indices) <= 2


def test_single_oversized_unit_still_ships():
    plan = schedule.plan_batches([10_000], batch_size=4,
                                 shape_fn=pow2_shape, token_budget=64)
    assert len(plan.batches) == 1
    assert plan.batches[0].indices == (0,)


def test_groups_stay_together():
    lengths = [10, 1000, 20, 990, 30, 40]
    groups = [[0, 1], [2, 3]]
    plan = schedule.plan_batches(lengths, batch_size=2, groups=groups)
    placed = {}
    for bi, b in enumerate(plan):
        for i in b.indices:
            placed[i] = bi
    assert placed[0] == placed[1]
    assert placed[2] == placed[3]


def test_exclusive_groups_one_batch_per_group():
    lengths = [10, 12, 20, 22, 5, 6]
    groups = [[0, 1], [2, 3], [4, 5]]
    plan = schedule.plan_batches(lengths, batch_size=64, groups=groups,
                                 exclusive_groups=True)
    assert len(plan.batches) == 3
    assert sorted(tuple(sorted(b.indices)) for b in plan) == \
        [(0, 1), (2, 3), (4, 5)]


def test_duplicate_row_in_groups_rejected():
    with pytest.raises(ValueError):
        schedule.plan_batches([1, 2, 3], batch_size=2,
                              groups=[[0, 1], [1, 2]])


def test_sequential_plan_matches_get_batches():
    lengths = [3, 9, 4, 8, 2, 7, 5]
    plan = schedule.sequential_plan(lengths, batch_size=3)
    assert [list(b.indices) for b in plan] == \
        [[0, 1, 2], [3, 4, 5], [6]]
    assert not plan.planned


def test_skewed_workload_meets_acceptance_bar():
    """The ISSUE acceptance criterion, host-only: on a skewed-length
    synthetic workload the planner shows >= 1.5x padding efficiency and
    strictly fewer distinct jit shape buckets than sequential chunking.
    Workload shape: dataset-order length clusters (subjects alternating
    short/medium prompt styles) with long few-shot outliers sprinkled
    through arrival order — the case where sequential chunking both drags
    whole batches to the outlier bucket AND fans out into many shapes."""
    import random
    rng = random.Random(3)
    lengths = []
    for block in range(8):
        lo, hi = (70, 128) if block % 2 == 0 else (300, 500)
        lengths += [rng.randint(lo, hi) for _ in range(46)]
    for _ in range(24):
        lengths.insert(rng.randrange(len(lengths)),
                       rng.randint(1400, 1900))
    planned = schedule.plan_batches(lengths, 16, shape_fn=pow2_shape)
    seq = schedule.sequential_plan(lengths, 16, shape_fn=pow2_shape)
    assert planned.stats.pad_eff >= 1.5 * seq.stats.pad_eff
    assert planned.stats.n_shapes < seq.stats.n_shapes
    assert planned.stats.real_tokens == seq.stats.real_tokens
    seen = sorted(i for b in planned for i in b.indices)
    assert seen == list(range(len(lengths)))


def test_default_budget_covers_bucketed_full_batch():
    """A non-pow2 batch_size buckets UP (12 -> B=16); the default budget
    must cover that full bucketed footprint, not split full batches."""
    lengths = [100] * 48
    plan = schedule.plan_batches(lengths, batch_size=12,
                                 shape_fn=pow2_shape)
    assert all(len(b.indices) == 12 for b in plan)
    assert len(plan.batches) == 4


def test_default_token_budget_fits_longest_row():
    lengths = [32] * 50 + [4096]
    budget = schedule.default_token_budget(lengths, 8, pow2_shape)
    b1, s1 = pow2_shape(1, 4096)
    assert budget >= b1 * s1


def test_execute_plan_pipelines_and_orders():
    """Double buffering: dispatch N+1 happens before collect N; every
    batch is still collected exactly once, in plan order."""
    lengths = [4, 4, 4, 4]
    plan = schedule.plan_batches(lengths, batch_size=1)
    events = []

    def dispatch(b):
        events.append(('dispatch', b.indices))
        return schedule.ReadyHandle(list(b.indices))

    def collect(b, result):
        events.append(('collect', tuple(result)))

    schedule.execute_plan(plan, dispatch, collect, depth=1)
    dispatched = [e for e in events if e[0] == 'dispatch']
    collected = [e for e in events if e[0] == 'collect']
    assert len(dispatched) == len(collected) == 4
    # batch 1 dispatched before batch 0 collected (one batch in flight)
    assert events[0][0] == 'dispatch' and events[1][0] == 'dispatch'
    assert events[2][0] == 'collect'
    # depth=0 degenerates to the strict legacy loop
    events.clear()
    schedule.execute_plan(plan, dispatch, collect, depth=0)
    assert [e[0] for e in events] == ['dispatch', 'collect'] * 4


def test_lazy_handle_fetches_once():
    from opencompass_tpu.models.base import _Lazy
    calls = []
    h = _Lazy(lambda: calls.append(1) or 'v')
    assert h.result() == 'v' and h.result() == 'v'
    assert calls == [1]


# ---------------------------------------------------------------------------
# FakeModel end-to-end equivalence
# ---------------------------------------------------------------------------

class SkewDataset(BaseDataset):
    """Questions with wildly different word counts so planned batches
    differ from arrival order."""

    @staticmethod
    def load(n_test=10):
        def q(i):
            if i % 3 == 0:
                return f'q{i} ' + 'very long padded question text ' * 12
            return f'q{i} short'
        train = Dataset.from_list([
            {'question': q(i), 'answer': 'A' if i % 2 == 0 else 'B'}
            for i in range(4)
        ])
        test = Dataset.from_list([
            {'question': q(i), 'answer': 'A' if i % 2 == 0 else 'B'}
            for i in range(n_test)
        ])
        return DatasetDict({'train': train, 'test': test})


READER_CFG = dict(input_columns=['question'], output_column='answer')


def _gen_setup(tmp_path, sub, batch_size=3, **kw):
    ds = SkewDataset(reader_cfg=READER_CFG)
    template = PromptTemplate('Q: {question}\nA: {answer}')
    model = FakeModel()
    inferencer = GenInferencer(
        model=model, max_out_len=5, batch_size=batch_size,
        output_json_filepath=str(tmp_path / sub), **kw)
    return ds, template, inferencer


def test_gen_plan_matches_sequential(tmp_path):
    ds, template, planned = _gen_setup(tmp_path, 'plan', batch_plan=True)
    _, _, seq = _gen_setup(tmp_path, 'seq', batch_plan=False)
    p_pred = planned.inference(ZeroRetriever(ds), prompt_template=template)
    s_pred = seq.inference(ZeroRetriever(ds), prompt_template=template)
    assert p_pred == s_pred
    saved_p = json.loads((tmp_path / 'plan' / 'predictions').read_text())
    saved_s = json.loads((tmp_path / 'seq' / 'predictions').read_text())
    assert saved_p == saved_s  # bit-identical rows, original order
    assert list(saved_p) == [str(i) for i in range(10)]


def test_gen_planner_reorders_batches(tmp_path):
    """Sanity that the planner actually changed execution order (else the
    equivalence test proves nothing)."""
    ds, template, inf = _gen_setup(tmp_path, 'plan', batch_plan=True)
    batches = []
    orig = FakeModel.generate

    class Spy(FakeModel):
        def generate(self, inputs, max_out_len):
            batches.append(len(inputs))
            return orig(self, inputs, max_out_len)
    inf.model = Spy()
    inf.inference(ZeroRetriever(ds), prompt_template=template)
    first_batch_rows = batches[0]
    assert len(batches) >= 2
    # the long rows (every 3rd idx) were packed together first
    assert first_batch_rows <= 3


def test_ppl_plan_matches_sequential(tmp_path):
    ds = SkewDataset(reader_cfg=READER_CFG)
    template = PromptTemplate({
        'A': '</E>Q: {question}\nA: A',
        'B': '</E>Q: {question}\nA: B',
    }, ice_token='</E>')
    canned = {f'q{i} ': 1.0 + i for i in range(0, 10, 2)}
    preds = {}
    for name, flag in (('plan', True), ('seq', False)):
        model = FakeModel(canned_ppls=dict(canned))
        inf = PPLInferencer(model=model, batch_size=3, batch_plan=flag,
                            output_json_filepath=str(tmp_path / name))
        preds[name] = inf.inference(ZeroRetriever(ds),
                                    prompt_template=template)
    assert preds['plan'] == preds['seq']
    saved_p = json.loads((tmp_path / 'plan' / 'predictions').read_text())
    saved_s = json.loads((tmp_path / 'seq' / 'predictions').read_text())
    assert saved_p == saved_s


def test_ppl_normalizing_plan_matches_sequential(tmp_path):
    ds = SkewDataset(reader_cfg=READER_CFG, n_test=6)
    template = PromptTemplate({
        'A': 'ctx {question}</S>answer A',
        'B': 'ctx {question}</S>answer B',
    }, sep_token='</S>')
    preds = {}
    for name, flag in (('plan', True), ('seq', False)):
        inf = PPLInferencer(model=FakeModel(), batch_size=2,
                            batch_plan=flag,
                            output_json_filepath=str(tmp_path / name))
        preds[name] = inf.inference(ZeroRetriever(ds),
                                    prompt_template=template,
                                    normalizing_str='NORM')
    assert preds['plan'] == preds['seq']


def test_ppl_item_major_groups_stay_intact(tmp_path):
    """With a shared-prefix model and planning on, every scoring batch
    still holds exactly one item's label variants."""
    ds = SkewDataset(reader_cfg=READER_CFG, n_test=6)
    template = PromptTemplate({
        'A': '</E>Q: {question}\nA: A',
        'B': '</E>Q: {question}\nA: B',
    }, ice_token='</E>')

    class SharedPrefixModel(FakeModel):
        shared_prefix_active = True
        supports_batch_plan = True

        def __init__(self, **kw):
            super().__init__(**kw)
            self.batches = []

        def get_ppl_from_template(self, templates, mask_length=None):
            self.batches.append([str(t) for t in templates])
            return super().get_ppl_from_template(templates)

    model = SharedPrefixModel()
    inf = PPLInferencer(model=model, batch_size=4, batch_plan=True,
                        output_json_filepath=str(tmp_path))
    preds = inf.inference(ZeroRetriever(ds), prompt_template=template)
    assert len(preds) == 6
    assert all(len(b) == 2 and 'A: A' in b[0] and 'A: B' in b[1]
               for b in model.batches)
    # every item scored exactly once, possibly out of order
    qs = sorted(b[0].split('Q: ')[1].split(' ')[0] for b in model.batches)
    assert qs == sorted(f'q{i}' for i in range(6))

    plain = FakeModel()
    inf2 = PPLInferencer(model=plain, batch_size=4, batch_plan=False,
                         output_json_filepath=str(tmp_path / 'b'))
    assert inf2.inference(ZeroRetriever(ds),
                          prompt_template=template) == preds


def test_clp_plan_matches_sequential(tmp_path):
    class ChoiceDataset(BaseDataset):
        @staticmethod
        def load():
            rows = [{'question': ('q%d ' % i) + 'pad ' * (20 if i % 3 == 0
                                                          else 1),
                     'choices': ['A', 'B'], 'answer': 'A'}
                    for i in range(8)]
            return DatasetDict({'train': Dataset.from_list(rows),
                                'test': Dataset.from_list(rows)})

    reader = dict(input_columns=['question'], output_column='answer')
    template = PromptTemplate('Q: {question}\nA:')
    preds = {}
    for name, flag in (('plan', True), ('seq', False)):
        ds = ChoiceDataset(reader_cfg=reader)
        inf = CLPInferencer(model=FakeModel(), batch_size=3,
                            batch_plan=flag,
                            output_json_filepath=str(tmp_path / name))
        preds[name] = inf.inference(ZeroRetriever(ds),
                                    prompt_template=template)
    assert preds['plan'] == preds['seq']
    saved_p = json.loads((tmp_path / 'plan' / 'predictions').read_text())
    saved_s = json.loads((tmp_path / 'seq' / 'predictions').read_text())
    assert saved_p == saved_s


# ---------------------------------------------------------------------------
# out-of-order resume + flush condition
# ---------------------------------------------------------------------------

def test_gen_resume_with_holes(tmp_path):
    """A killed out-of-order run leaves a tmp file with holes; resume
    must fill exactly the missing indices and keep the saved rows."""
    ds, template, inf = _gen_setup(tmp_path, 'r', batch_plan=True)
    scratch = tmp_path / 'r' / 'tmp_predictions'
    scratch.parent.mkdir(parents=True, exist_ok=True)
    scratch.write_text(json.dumps({
        '7': {'origin_prompt': 'p7', 'prediction': 'SAVED7'},
        '2': {'origin_prompt': 'p2', 'prediction': 'SAVED2'},
    }))
    preds = inf.inference(ZeroRetriever(ds), prompt_template=template)
    assert len(preds) == 10
    assert preds[2] == 'SAVED2' and preds[7] == 'SAVED7'
    saved = json.loads((tmp_path / 'r' / 'predictions').read_text())
    assert list(saved) == [str(i) for i in range(10)]
    assert saved['2']['prediction'] == 'SAVED2'
    assert all(saved[str(i)]['prediction'].startswith('fake-')
               for i in range(10) if i not in (2, 7))
    assert not scratch.exists()


def test_gen_resume_equals_fresh_run(tmp_path):
    """Kill-and-resume mid-plan converges to the same predictions file
    as an uninterrupted run."""
    ds, template, fresh = _gen_setup(tmp_path, 'fresh', batch_plan=True)
    fresh_preds = fresh.inference(ZeroRetriever(ds),
                                  prompt_template=template)
    # simulate a mid-plan kill: seed the tmp with 4 arbitrary completed
    # rows copied from the fresh run
    done = json.loads((tmp_path / 'fresh' / 'predictions').read_text())
    partial = {k: done[k] for k in ('9', '0', '4', '6')}
    _, _, resumed = _gen_setup(tmp_path, 'resume', batch_plan=True)
    scratch = tmp_path / 'resume' / 'tmp_predictions'
    scratch.parent.mkdir(parents=True, exist_ok=True)
    scratch.write_text(json.dumps(partial))
    resumed_preds = resumed.inference(ZeroRetriever(ds),
                                      prompt_template=template)
    assert resumed_preds == fresh_preds
    assert json.loads(
        (tmp_path / 'resume' / 'predictions').read_text()) == done


def test_gen_flush_every_distance_not_modulo(tmp_path, monkeypatch):
    """save_every=3 with batch_size=2: the old ``cursor % save_every``
    condition never fired (cursor always even); the distance condition
    must flush ~every 2 batches."""
    from opencompass_tpu.icl.inferencers import base as inf_base
    flushes = []
    orig = inf_base.GenInferencerOutputHandler.write_to_json

    def spy(self, save_dir, filename):
        if filename.startswith('tmp_'):
            flushes.append(len(self.results_dict))
        return orig(self, save_dir, filename)
    monkeypatch.setattr(inf_base.GenInferencerOutputHandler,
                        'write_to_json', spy)
    ds, template, inf = _gen_setup(tmp_path, 'f', batch_size=2,
                                   batch_plan=False, save_every=3)
    inf.inference(ZeroRetriever(ds), prompt_template=template)
    assert flushes, 'no tmp flush happened at all'
    # 10 rows in batches of 2: flush fires at 4, 8 (distance >= 3),
    # where cursor % 3 == 0 would never have fired
    assert flushes == [4, 8]


# ---------------------------------------------------------------------------
# tiny JaxLM integration (real async dispatch + shape buckets + counters)
# ---------------------------------------------------------------------------

def _jax_toy_dataset():
    class ToyDS(BaseDataset):
        @staticmethod
        def load():
            def q(i):
                if i % 3 == 0:
                    return (f'question number {i} '
                            + 'plus lots of extra filler words to push '
                              'the token count into a bigger bucket ' * 3)
                return f'q{i}?'
            rows = [{'question': q(i), 'answer': str(i)}
                    for i in range(6)]
            return DatasetDict({'train': Dataset.from_list(rows),
                                'test': Dataset.from_list(rows)})
    return ToyDS(reader_cfg=READER_CFG)


def test_jax_lm_gen_plan_matches_sequential(tmp_path):
    from opencompass_tpu.models import JaxLM
    ds = _jax_toy_dataset()
    template = PromptTemplate('Q: {question}\nA: {answer}')
    out = {}
    models = {}
    for name, flag in (('plan', True), ('seq', False)):
        lm = JaxLM(config='tiny', max_seq_len=512)
        models[name] = lm
        inf = GenInferencer(model=lm, max_out_len=6, batch_size=2,
                            batch_plan=flag,
                            output_json_filepath=str(tmp_path / name))
        out[name] = inf.inference(ZeroRetriever(ds),
                                  prompt_template=template)
    assert out['plan'] == out['seq']
    saved_p = json.loads((tmp_path / 'plan' / 'predictions').read_text())
    saved_s = json.loads((tmp_path / 'seq' / 'predictions').read_text())
    assert saved_p == saved_s
    # the planner padded strictly fewer dead slots on this skewed set
    assert models['plan'].perf.pad_tokens < models['seq'].perf.pad_tokens
    assert models['plan'].perf.planned_shapes >= 1
    assert models['seq'].perf.planned_shapes == 0


def test_jax_lm_ppl_plan_matches_sequential(tmp_path):
    from opencompass_tpu.models import JaxLM
    ds = _jax_toy_dataset()
    template = PromptTemplate({
        'A': '</E>Q: {question}\nA: yes', 'B': '</E>Q: {question}\nA: no',
    }, ice_token='</E>')
    preds = {}
    for name, flag in (('plan', True), ('seq', False)):
        lm = JaxLM(config='tiny', max_seq_len=512, shared_prefix=False)
        inf = PPLInferencer(model=lm, batch_size=2, batch_plan=flag,
                            output_json_filepath=str(tmp_path / name))
        preds[name] = inf.inference(ZeroRetriever(ds),
                                    prompt_template=template)
    assert preds['plan'] == preds['seq']
    saved_p = json.loads((tmp_path / 'plan' / 'predictions').read_text())
    saved_s = json.loads((tmp_path / 'seq' / 'predictions').read_text())
    assert list(saved_p) == list(saved_s)
    for k in saved_p:
        for label in ('label: A', 'label: B'):
            assert saved_p[k][label]['PPL'] == pytest.approx(
                saved_s[k][label]['PPL'], abs=1e-3)


def test_jax_lm_plan_shape_is_padder_truth():
    """plan_shape and _pad_ids must agree — the planner's cost model is
    the padder's actual geometry."""
    from opencompass_tpu.models import JaxLM
    lm = JaxLM(config='tiny', max_seq_len=512, tokenizer_only=True)
    for rows, longest in ((1, 5), (3, 100), (5, 400), (9, 4000)):
        ids = [[1] * min(longest, 512)] * rows
        tokens, _ = lm._pad_ids(ids, left_pad=False, max_len=512)
        assert tokens.shape == lm.plan_shape(rows, longest)


def test_acceptance_with_real_jax_lm_geometry():
    """The skewed-workload acceptance bar against the real JaxLM bucket
    geometry (tokenizer_only: host-side, no weights)."""
    import random
    from opencompass_tpu.models import JaxLM
    lm = JaxLM(config='tiny', max_seq_len=2048, tokenizer_only=True)
    rng = random.Random(3)
    lengths = []
    for block in range(8):
        lo, hi = (70, 128) if block % 2 == 0 else (300, 500)
        lengths += [rng.randint(lo, hi) for _ in range(46)]
    for _ in range(24):
        lengths.insert(rng.randrange(len(lengths)),
                       rng.randint(1400, 1900))
    planned = schedule.plan_batches(lengths, 16, shape_fn=lm.plan_shape)
    seq = schedule.sequential_plan(lengths, 16, shape_fn=lm.plan_shape)
    assert planned.stats.pad_eff >= 1.5 * seq.stats.pad_eff
    assert planned.stats.n_shapes < seq.stats.n_shapes


def test_cli_plan_dry_run_smoke():
    """`cli plan` renders per-task planned-vs-sequential stats for the
    hermetic demo config without touching a device."""
    import os
    from opencompass_tpu.utils.plan_preview import main
    cfg = os.path.join(os.path.dirname(__file__), '..', 'configs',
                       'eval_demo.py')
    import io
    import json as _json
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main([cfg, '--json'])
    assert rc == 0
    out = _json.loads(buf.getvalue())
    assert out['v'] == 1 and out['tasks']
    task = out['tasks'][0]
    assert {'model', 'dataset', 'rows', 'planned',
            'sequential'} <= set(task)
    assert task['planned']['n_shapes'] >= 1
    assert task['planned']['real_tokens'] == \
        task['sequential']['real_tokens']


def test_perf_record_carries_planner_fields(tmp_path):
    from opencompass_tpu.utils.perf import TaskProfiler
    model = FakeModel()
    out = str(tmp_path / 'perf.json')
    with TaskProfiler(model, out_path=out):
        model.get_ppl(['a b c'] * 2)
        model.perf.pad_tokens += 6
        model.perf.overlap_seconds += 0.5
        model.perf.planned_shapes += 2
    rec = json.loads(open(out).read())
    assert rec['pad_tokens'] == 6
    assert rec['pad_eff'] == pytest.approx(6 / 12.0)
    assert rec['overlap_seconds'] == 0.5
    assert rec['planned_shapes'] == 2
