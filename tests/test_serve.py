"""Evaluation-as-a-service: sweep queue durability, worker-pool
scheduling, the HTTP front door, worker lifecycle (idle TTL / SIGTERM
drain), and the daemon end-to-end (slow tier)."""
import hashlib
import json
import os
import os.path as osp
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
DEMO_CFG = osp.join(REPO, 'configs', 'eval_demo.py')


# -- durable FIFO sweep queue ----------------------------------------------

def _queue(tmp_path):
    from opencompass_tpu.serve.queue import SweepQueue
    return SweepQueue(str(tmp_path / 'queue'))


def test_queue_fifo_and_terminal_ops(tmp_path):
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/cfg/a.py')['id']
    b = q.enqueue(config_path='/cfg/b.py', mode='infer')['id']
    c = q.enqueue(config_text='datasets = []\nmodels = []\n')['id']
    state = q.state()
    assert list(state) == [a, b, c]          # FIFO == journal order
    assert all(r['status'] == 'queued' for r in state.values())
    # inline config persisted to a daemon-readable file
    assert osp.isfile(state[c]['config_path'])
    assert 'datasets' in open(state[c]['config_path']).read()
    assert q.depth() == 3

    first = q.claim_next(owner='t')
    assert first['id'] == a                  # oldest first
    assert q.status(a)['status'] == 'running'
    q.mark_done(a, ok=True, detail={'n_tasks': 2})
    assert q.status(a)['status'] == 'done'
    assert q.status(a)['detail'] == {'n_tasks': 2}

    second = q.claim_next(owner='t')
    assert second['id'] == b
    q.mark_done(b, ok=False)
    assert q.status(b)['status'] == 'failed'
    assert q.counts() == {'queued': 1, 'running': 0, 'done': 1,
                          'failed': 1, 'cancelled': 0}


def test_queue_concurrent_enqueue_two_clients(tmp_path):
    """Two clients (threads, each with its own SweepQueue handle on the
    same directory) enqueue concurrently: every record lands, order is
    journal order, and the drain sees all of them FIFO."""
    from opencompass_tpu.serve.queue import SweepQueue
    root = str(tmp_path / 'queue')
    ids = {0: [], 1: []}

    def client(n):
        q = SweepQueue(root)
        for i in range(20):
            ids[n].append(
                q.enqueue(config_path=f'/cfg/c{n}-{i}.py')['id'])

    threads = [threading.Thread(target=client, args=(n,))
               for n in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q = SweepQueue(root)
    state = list(q.state())
    assert len(state) == 40
    assert set(state) == set(ids[0]) | set(ids[1])
    # per-client FIFO survives the interleave
    for n in (0, 1):
        order = [s for s in state if s in set(ids[n])]
        assert order == ids[n]
    drained = []
    while True:
        rec = q.claim_next(owner='drain')
        if rec is None:
            break
        drained.append(rec['id'])
        q.mark_done(rec['id'])
    assert drained == state


def test_queue_claim_is_exclusive(tmp_path):
    """Two daemons on one queue directory: O_EXCL arbitrates — each
    sweep is claimed exactly once."""
    from opencompass_tpu.serve.queue import SweepQueue
    root = str(tmp_path / 'queue')
    q1, q2 = SweepQueue(root), SweepQueue(root)
    ids = [q1.enqueue(config_path=f'/c{i}.py')['id'] for i in range(2)]
    first = q1.claim_next(owner='d1')
    second = q2.claim_next(owner='d2')
    assert {first['id'], second['id']} == set(ids)
    assert q2.claim_next(owner='d2') is None   # both taken


def test_queue_cancel_only_while_queued(tmp_path):
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/a.py')['id']
    b = q.enqueue(config_path='/b.py')['id']
    q.claim_next(owner='d')                    # a now running (live pid)
    assert q.cancel(a) is False                # running: not cancellable
    assert q.cancel(b) is True
    assert q.status(b)['status'] == 'cancelled'
    assert q.cancel(b) is False                # already terminal
    assert q.cancel('sw-nope') is False        # unknown
    assert q.claim_next(owner='d2') is None    # nothing queued remains


def test_queue_stale_claim_recovery(tmp_path):
    """A claim whose owner pid is dead re-queues the sweep — the whole
    kill -9 resume story at queue level."""
    import json as jsonlib
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/a.py')['id']
    # a dead daemon's claim: a pid that existed and exited
    proc = subprocess.Popen([sys.executable, '-c', 'pass'])
    proc.wait()
    with open(q._claim_path(a), 'w') as f:
        jsonlib.dump({'v': 1, 'id': a, 'owner': 'dead',
                      'pid': proc.pid, 'ts': 0}, f)
    rec = q.status(a)
    assert rec['status'] == 'queued'
    assert rec.get('stale_claim') is True
    assert q.recover() == [a]
    claimed = q.claim_next(owner='d2')
    assert claimed['id'] == a
    assert q.status(a)['status'] == 'running'


def test_queue_torn_journal_line_recovery(tmp_path):
    """kill -9 can tear at most the final journal line; replay skips it
    and — because a reopened queue seals the torn tail — the next
    append lands on its own line instead of being absorbed."""
    from opencompass_tpu.serve.queue import SweepQueue
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/a.py')['id']
    b = q.enqueue(config_path='/b.py')['id']
    with open(q.journal_path, 'a') as f:
        f.write('{"v": 1, "op": "enqueue", "id": "sw-torn", "conf')
    state = q.state()
    assert list(state) == [a, b]
    assert 'sw-torn' not in state
    # a restarted daemon (fresh handle) seals the tear, so its appends
    # start clean on their own line
    q2 = SweepQueue(q.root)
    c = q2.enqueue(config_path='/c.py')['id']
    assert list(q2.state()) == [a, b, c]
    assert list(q.state()) == [a, b, c]


def test_queue_mid_life_torn_tail_reseal(tmp_path):
    """A torn line created DURING the daemon's lifetime (an external
    CLI client killed mid-append) must not absorb the daemon's next
    append — every write re-seals the tail, not just __init__."""
    from opencompass_tpu.serve.queue import SweepQueue
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/a.py')['id']
    with open(q.journal_path, 'a') as f:
        f.write('{"v": 1, "op": "enqueue", "id": "sw-torn", "conf')
    b = q.enqueue(config_path='/b.py')['id']   # same live handle
    q.mark_done(a)
    state = SweepQueue(q.root).state()         # full replay from disk
    assert list(state) == [a, b]
    assert state[a]['status'] == 'done'
    assert state[b]['status'] == 'queued'
    assert 'sw-torn' not in state


def test_queue_claim_break_rechecks_live_takeover(tmp_path):
    """Breaking a stale claim must re-check the file under the claims
    flock: if another daemon broke it and took the sweep after our
    state() snapshot, unlinking would delete the winner's LIVE claim
    and both daemons would run the sweep."""
    import json as jsonlib
    q = _queue(tmp_path)
    a = q.enqueue(config_path='/a.py')['id']
    proc = subprocess.Popen([sys.executable, '-c', 'pass'])
    proc.wait()
    with open(q._claim_path(a), 'w') as f:
        jsonlib.dump({'v': 1, 'id': a, 'owner': 'dead',
                      'pid': proc.pid, 'ts': 0}, f)
    stale_snap = q.state()
    assert stale_snap[a].get('stale_claim') is True
    # another daemon wins the break and claims: live pid on disk now
    live = {'v': 1, 'id': a, 'owner': 'winner', 'pid': os.getpid(),
            'ts': 1}
    with open(q._claim_path(a), 'w') as f:
        jsonlib.dump(live, f)
    q.state = lambda: stale_snap            # freeze the stale snapshot
    assert q.claim_next(owner='loser') is None
    assert q.recover() == []
    assert q.read_claim(a) == live          # winner's claim untouched


# -- worker pool scheduling (fake handles) ---------------------------------

class _FakeHandle:
    """Quacks like WorkerHandle without a subprocess."""
    spawned = []

    def __init__(self, env, log_path):
        self.env, self.log_path = env, log_path
        self.dead = False
        self.proc = type('P', (), {'pid': 4242,
                                   'poll': staticmethod(lambda: None)})()
        self.requests = []
        self.shutdowns = 0
        _FakeHandle.spawned.append(self)

    def request(self, msg, timeout=None):
        self.requests.append(msg)
        return {'ok': True}

    def request_watched(self, msg, **kw):
        return self.request(msg)

    def shutdown(self, timeout=10.0):
        self.shutdowns += 1
        self.dead = True
        self.proc.poll = lambda: 0

    def kill(self):
        self.dead = True
        self.proc.poll = lambda: 0


@pytest.fixture()
def fake_worker(monkeypatch):
    from opencompass_tpu.runners import worker as workermod
    _FakeHandle.spawned = []
    monkeypatch.setattr(workermod, 'WorkerHandle', _FakeHandle)
    return _FakeHandle


def _spawn(chip_ids):
    return {'CHIPS': ','.join(map(str, chip_ids))}, '/dev/null'


def test_pool_lease_reuse_and_release(fake_worker):
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=None)
    w1 = pool.acquire('m1', _spawn)
    w2 = pool.acquire('m1', _spawn)     # same key, concurrent lease
    assert w1 is w2
    assert w1.in_use == 2
    assert len(fake_worker.spawned) == 1
    pool.release(w1)
    pool.release(w2)
    assert w1.in_use == 0
    w3 = pool.acquire('m1', _spawn)     # released, still resident
    assert w3 is w1
    stats = pool.stats()
    assert stats['spawns'] == 1
    assert stats['reuses'] == 2
    assert stats['resident'] == 1
    pool.shutdown()
    assert w1.handle.shutdowns == 1
    assert pool.resident_count == 0


def test_pool_chip_accounting(fake_worker):
    """Chips come from the runner's allocator at spawn and go back at
    retire — pooled workers and one-shot tasks share one ledger."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    ledger = {'out': 0}

    def alloc(n):
        ledger['out'] += n
        return list(range(n))

    def free(ids):
        ledger['out'] -= len(ids)

    pool = WorkerPool(alloc=alloc, free=free)
    w = pool.acquire('m1', _spawn, devices=2)
    assert ledger['out'] == 2
    pool.release(w)
    assert ledger['out'] == 2           # residency holds the chips
    pool.shutdown()
    assert ledger['out'] == 0


def test_pool_idle_ttl_reap(fake_worker):
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=10.0)
    w1 = pool.acquire('m1', _spawn)
    w2 = pool.acquire('m2', _spawn)
    pool.release(w1)                    # idle from now
    now = time.monotonic()
    assert pool.reap_idle(now=now + 5) == []        # not yet
    assert pool.reap_idle(now=now + 11) == ['m1']   # past TTL
    assert w1.handle.shutdowns == 1                 # graceful retire
    # w2 still leased: never reaped, no matter how idle
    assert pool.reap_idle(now=now + 1000) == []
    assert pool.resident_count == 1
    pool.shutdown()


def test_pool_reaps_quietly_dead_worker(fake_worker):
    """A worker that self-exited (its own idle TTL, a crash) is swept
    out by the reaper even before the pool TTL."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=1e9)
    w = pool.acquire('m1', _spawn)
    pool.release(w)
    w.handle.dead = True                # died on its own
    assert pool.reap_idle() == ['m1']
    assert pool.resident_count == 0


def test_pool_capacity_eviction(fake_worker):
    """Past max_resident the longest-idle unleased worker is evicted;
    leased workers are never victims."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=None, max_resident=2)
    w1 = pool.acquire('m1', _spawn)
    pool.release(w1)
    w1.last_used -= 100                 # clearly the oldest
    w2 = pool.acquire('m2', _spawn)
    pool.acquire('m3', _spawn)          # over capacity: evict m1
    assert pool.resident_count == 2
    assert w1.handle.shutdowns == 1
    keys = set(pool.stats()['workers'])
    assert keys == {'m2', 'm3'}
    # m2 is leased: acquiring a 4th key must evict m3, not m2
    pool.release(pool.acquire('m3', _spawn))
    pool.acquire('m4', _spawn)
    assert 'm2' in pool.stats()['workers']
    pool.shutdown()


def test_pool_acquire_retires_quietly_dead_worker(fake_worker):
    """acquire() on a key whose resident quietly died must retire the
    corpse — freeing its chips — not just drop the dict entry, or the
    slot ledger leaks and the replacement spawn can block forever on
    chips nobody will ever release."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    ledger = {'out': 0}

    def alloc(n):
        ledger['out'] += n
        return list(range(n))

    pool = WorkerPool(alloc=alloc,
                      free=lambda ids: ledger.__setitem__(
                          'out', ledger['out'] - len(ids)))
    w = pool.acquire('m1', _spawn, devices=2)
    pool.release(w)
    w.handle.dead = True                # self-exited (own TTL / crash)
    w2 = pool.acquire('m1', _spawn, devices=2)
    assert w2 is not w
    assert ledger['out'] == 2           # corpse's chips were freed
    pool.shutdown()
    assert ledger['out'] == 0


def test_pool_capacity_eviction_frees_chips_before_alloc(fake_worker):
    """With max_resident, the evictee must be retired BEFORE the new
    worker's chip allocation — its chips may be the very ones alloc()
    would otherwise block on (2-chip host, 2-chip models, cap 1)."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    ledger = {'out': 0}

    def alloc(n):
        # the real allocator blocks; here over-subscription = the bug
        assert ledger['out'] + n <= 2, 'alloc would deadlock'
        ledger['out'] += n
        return list(range(n))

    pool = WorkerPool(idle_ttl_s=None, max_resident=1, alloc=alloc,
                      free=lambda ids: ledger.__setitem__(
                          'out', ledger['out'] - len(ids)))
    w1 = pool.acquire('m1', _spawn, devices=2)
    pool.release(w1)
    w2 = pool.acquire('m2', _spawn, devices=2)   # must evict m1 first
    assert w1.handle.shutdowns == 1
    assert set(pool.stats()['workers']) == {'m2'}
    assert ledger['out'] == 2
    pool.release(w2)
    pool.shutdown()


def test_worker_busy_is_backpressure_not_a_corpse(fake_worker):
    """A bounded request() that cannot take the channel lock raises
    WorkerBusyError — distinct from WorkerError, so the daemon releases
    the lease instead of discarding (killing) a healthy mid-sweep
    worker."""
    from opencompass_tpu.runners.worker import WorkerError
    from opencompass_tpu.serve.scheduler import (WorkerBusyError,
                                                 WorkerPool)
    pool = WorkerPool(idle_ttl_s=None)
    w = pool.acquire('m1', _spawn)
    assert not issubclass(WorkerBusyError, WorkerError)
    hold = threading.Event()
    done = threading.Event()

    def occupant():
        with w.lock:                    # a sweep round-trip in flight
            hold.set()
            done.wait(10)

    t = threading.Thread(target=occupant)
    t.start()
    assert hold.wait(5)
    try:
        with pytest.raises(WorkerBusyError):
            w.request({'cmd': 'ping'}, timeout=0.05)
    finally:
        done.set()
        t.join()
    # unbounded / post-release requests still work
    assert w.request({'cmd': 'ping'}) == {'ok': True}
    pool.shutdown()


def test_pool_discard_dead_worker(fake_worker):
    from opencompass_tpu.serve.scheduler import WorkerPool
    freed = []
    pool = WorkerPool(alloc=lambda n: [7], free=freed.extend)
    w = pool.acquire('m1', _spawn, devices=1)
    w.handle.dead = True
    pool.discard(w)
    assert pool.resident_count == 0
    assert freed == [7]
    # next acquire spawns fresh
    w2 = pool.acquire('m1', _spawn, devices=1)
    assert w2 is not w
    pool.shutdown()


def test_pool_leased_underprovisioned_worker_spawns_bigger(fake_worker):
    """A leased under-provisioned resident (0-chip interactive worker,
    in flight) must NOT be handed to a caller that needs chips — the
    pool spawns a bigger sibling and orphans the small one, which the
    reaper retires once its leases drain."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    ledger = {'out': 0}

    def alloc(n):
        ledger['out'] += n
        return list(range(n))

    pool = WorkerPool(idle_ttl_s=None, alloc=alloc,
                      free=lambda ids: ledger.__setitem__(
                          'out', ledger['out'] - len(ids)))
    w_small = pool.acquire('m1', _spawn)            # interactive, 0 chips
    w_big = pool.acquire('m1', _spawn, devices=2)   # sweep group
    assert w_big is not w_small
    assert w_big.devices == 2 and ledger['out'] == 2
    stats = pool.stats()
    assert stats['resident'] == 1 and stats['orphans'] == 1
    # new leases land on the big worker; the orphan is unreachable
    pool.release(pool.acquire('m1', _spawn))
    assert w_big.in_use == 1 and w_small.in_use == 1
    # orphan survives reaping while leased, retires once drained
    assert pool.reap_idle() == []
    pool.release(w_small)
    assert pool.reap_idle() == ['m1']
    assert w_small.handle.shutdowns == 1
    assert pool.stats()['orphans'] == 0
    pool.release(w_big)
    pool.shutdown()
    assert ledger['out'] == 0


def test_pool_retire_frees_chips_exactly_once(fake_worker):
    """shutdown() racing a lease-holder's discard() must not free the
    same chip_ids twice — a double free would mark chips re-allocated
    to a new worker as free and hand one chip to two owners."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    freed = []
    pool = WorkerPool(alloc=lambda n: [3, 4], free=freed.extend)
    w = pool.acquire('m1', _spawn, devices=2)
    pool.shutdown()                 # engine stop with the lease in flight
    pool.discard(w)                 # holder sees the killed channel
    assert freed == [3, 4]


def test_pool_alloc_timeout_surfaces(fake_worker):
    """acquire(alloc_timeout_s=...) propagates the allocator's
    TimeoutError instead of parking the caller — the interactive path's
    bound when sweeps hold every chip.  Sweeps pass no timeout and keep
    the blocking contract."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    calls = []

    def alloc(n, timeout=None):
        calls.append(timeout)
        if timeout is not None:
            raise TimeoutError(f'no {n} free slot(s) within {timeout}s')
        return list(range(n))

    pool = WorkerPool(idle_ttl_s=None, alloc=alloc,
                      free=lambda ids: None)
    with pytest.raises(TimeoutError):
        pool.acquire('m1', _spawn, devices=2, alloc_timeout_s=0.1)
    assert pool.resident_count == 0
    w = pool.acquire('m1', _spawn, devices=2)   # unbounded sweep path
    assert w.chip_ids == [0, 1]
    assert calls == [0.1, None]
    pool.shutdown()


def test_acquire_slots_timeout():
    """LocalRunner._acquire_slots with a timeout raises instead of
    spinning forever when the chips never free."""
    from opencompass_tpu.runners import LocalRunner
    runner = LocalRunner(dict(type='OpenICLInferTask'), num_devices=1)
    ids = runner._acquire_slots(1)
    with pytest.raises(TimeoutError):
        runner._acquire_slots(1, timeout=1.5)
    runner._release_slots(ids)
    assert runner._acquire_slots(1, timeout=5.0) == ids
    runner._release_slots(ids)


def test_request_timeout_is_total_budget(fake_worker):
    """The caller's timeout covers lock wait + protocol round-trip:
    time spent queued behind a sweep round-trip is deducted from the
    handle request's share, so worst-case wall time is ~timeout, not
    2x timeout."""
    from opencompass_tpu.serve.scheduler import WorkerPool
    pool = WorkerPool(idle_ttl_s=None)
    w = pool.acquire('m1', _spawn)
    seen = {}
    orig = w.handle.request
    w.handle.request = lambda msg, timeout=None: (
        seen.__setitem__('timeout', timeout) or orig(msg))
    hold = threading.Event()

    def occupant():
        with w.lock:
            hold.set()
            time.sleep(0.5)

    t = threading.Thread(target=occupant)
    t.start()
    assert hold.wait(5)
    assert w.request({'cmd': 'ping'}, timeout=5.0) == {'ok': True}
    t.join()
    assert seen['timeout'] is not None
    assert 1.0 <= seen['timeout'] <= 4.9
    pool.shutdown()


# -- HTTP server: route dispatch + readiness -------------------------------

def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        payload = exc.read()
        try:
            payload = json.loads(payload)
        except ValueError:
            payload = payload.decode('utf-8', 'replace')
        return exc.code, payload


def test_http_routes_and_readiness(tmp_path):
    """Registered routes dispatch ahead of the built-ins (exact and
    prefix keys, every method) and a readiness probe turns /healthz
    into a 200/503 gate."""
    from opencompass_tpu.obs.promexport import ObsHTTPServer
    ready = {'ready': False}
    calls = []

    def echo(path, query, body):
        calls.append((path, query, body))
        return 201, {'path': path, 'body': body.decode() or None}

    server = ObsHTTPServer(
        str(tmp_path / 'obs'), port=0,
        routes={('POST', '/v1/things'): echo,
                ('GET', '/v1/things/'): echo,
                ('DELETE', '/v1/things/'): echo},
        readiness=lambda: dict(ready),
        status_fn=lambda: {'overall': {},
                           'serve': {'queue_depth': 3}})
    port = server.start()
    assert port
    base = f'http://127.0.0.1:{port}'
    try:
        code, rep = _http('GET', base + '/healthz')
        assert code == 503 and rep['ready'] is False
        ready['ready'] = True
        code, rep = _http('GET', base + '/healthz')
        assert code == 200 and rep['ready'] is True

        code, rep = _http('POST', base + '/v1/things', {'x': 1})
        assert code == 201 and json.loads(rep['body']) == {'x': 1}
        code, rep = _http('GET', base + '/v1/things/abc?full=1')
        assert code == 201 and rep['path'] == '/v1/things/abc'
        code, rep = _http('DELETE', base + '/v1/things/abc')
        assert code == 201
        # built-ins still answer; status_fn override feeds /status and
        # the /metrics serve gauges
        code, rep = _http('GET', base + '/status')
        assert code == 200 and rep['serve']['queue_depth'] == 3
        req = urllib.request.Request(base + '/metrics')
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert 'oct_serve_queue_depth 3' in text
        code, _ = _http('POST', base + '/nope', {})
        assert code == 404
    finally:
        server.stop()


def test_serve_route_handlers_validation(tmp_path):
    """Control/data-plane handlers against a stub engine: request
    validation, enqueue, cancel semantics, unknown model."""
    from opencompass_tpu.serve.http import build_routes
    from opencompass_tpu.serve.queue import SweepQueue

    class StubEngine:
        def __init__(self):
            self.queue = SweepQueue(str(tmp_path / 'q'))

        def models(self):
            return ['fake-demo']

        def sweep_status(self, sweep_id):
            return self.queue.status(sweep_id)

        def complete(self, model, prompts, max_out_len=16, **kw):
            if model not in self.models():
                raise KeyError(model)
            return {'ok': True, 'completions': [f'echo:{p}'
                                                for p in prompts],
                    'store_hits': 0, 'device_rows': len(prompts),
                    'built': False, 'prompt_tokens': 2,
                    'completion_tokens': 2, 'elapsed_seconds': 0.01,
                    'id': kw.get('response_id'),
                    'request_id': kw.get('request_id')}

    engine = StubEngine()
    routes = build_routes(engine)
    post = routes[('POST', '/v1/sweeps')]
    get_one = routes[('GET', '/v1/sweeps/')]
    delete = routes[('DELETE', '/v1/sweeps/')]
    completions = routes[('POST', '/v1/completions')]

    code, rep = post('/v1/sweeps', '', b'not json')
    assert code == 400
    code, rep = post('/v1/sweeps', '', b'{}')
    assert code == 400
    code, rep = post('/v1/sweeps', '',
                     json.dumps({'config': 'models = []\n',
                                 'mode': 'infer'}).encode())
    assert code == 202 and rep['status'] == 'queued'
    sid = rep['id']
    code, rep = get_one(f'/v1/sweeps/{sid}', '', b'')
    assert code == 200 and rep['status'] == 'queued'
    code, rep = get_one('/v1/sweeps/sw-unknown', '', b'')
    assert code == 404
    code, rep = delete(f'/v1/sweeps/{sid}', '', b'')
    assert code == 200 and rep['status'] == 'cancelled'
    code, rep = delete(f'/v1/sweeps/{sid}', '', b'')
    assert code == 409                      # already terminal

    code, rep = completions('/v1/completions', '', b'{}')
    assert code == 400
    code, rep = completions(
        '/v1/completions', '',
        json.dumps({'model': 'nope', 'prompt': 'hi'}).encode())
    assert code == 404 and rep['error']['type'] == 'model_not_found'
    code, rep = completions(
        '/v1/completions', '',
        json.dumps({'model': 'fake-demo', 'prompt': 'hi',
                    'max_tokens': 4}).encode())
    assert code == 200
    assert rep['object'] == 'text_completion'
    assert rep['choices'][0]['text'] == 'echo:hi'
    assert rep['usage']['total_tokens'] == 4
    assert rep['oct']['device_rows'] == 1


def test_sweep_task_status_slices_run_snapshot():
    from opencompass_tpu.obs.live import sweep_task_status
    snap = {'ts': 1.0, 'tasks': {
        'OpenICLInfer[a]': {'state': 'ok', 'progress': 1.0,
                            'rows_done': 4, 'rows_cached': 4},
        'OpenICLInfer[b]': {'state': 'running', 'progress': 0.5,
                            'rows_done': 2, 'rows_cached': 0},
        'OpenICLInfer[other-sweep]': {'state': 'running',
                                      'progress': 0.1},
    }}
    out = sweep_task_status(
        snap, ['OpenICLInfer[a]', 'OpenICLInfer[b]',
               'OpenICLInfer[pending]'])
    assert set(out['tasks']) == {'OpenICLInfer[a]', 'OpenICLInfer[b]'}
    assert out['missing'] == ['OpenICLInfer[pending]']
    o = out['overall']
    assert o['n_tasks'] == 2
    assert o['progress'] == 0.75
    assert o['ok'] == 1 and o['running'] == 1
    # the other sweep's task must not leak into this sweep's fold
    assert 'OpenICLInfer[other-sweep]' not in out['tasks']


# -- worker lifecycle: idle TTL + SIGTERM drain (subprocess: slow) ---------

def _worker_env(extra=None):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['PYTHONPATH'] = REPO + (
        ':' + env['PYTHONPATH'] if env.get('PYTHONPATH') else '')
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_worker_idle_ttl_self_exit(tmp_path):
    """A worker nobody talks to for OCT_WORKER_IDLE_TTL_S exits on its
    own with code 0 — a leaked worker cannot hold chips forever."""
    from opencompass_tpu.runners.worker import WorkerHandle
    log = str(tmp_path / 'worker.log')
    handle = WorkerHandle(_worker_env({'OCT_WORKER_IDLE_TTL_S': '1'}),
                          log)
    try:
        assert handle.request({'cmd': 'ping'},
                              timeout=30)['pong'] is True
        handle.proc.wait(timeout=30)
        assert handle.proc.returncode == 0
        assert 'exiting (idle_ttl)' in open(log).read()
    finally:
        handle.kill()


@pytest.mark.slow
def test_worker_sigterm_graceful_drain(tmp_path):
    """SIGTERM finishes the in-flight request (its response is still
    delivered), then the worker exits 0 — the reaper can never lose
    committed work."""
    from opencompass_tpu.runners.worker import WorkerHandle
    log = str(tmp_path / 'worker.log')
    handle = WorkerHandle(_worker_env(), log)
    try:
        assert handle.request({'cmd': 'ping'},
                              timeout=30)['pong'] is True
        # in-flight request, then SIGTERM racing it: the drain contract
        # says the response still arrives and exit is clean
        from opencompass_tpu.runners.worker import read_frame, \
            write_frame
        write_frame(handle.proc.stdin,
                    {'cmd': 'complete',
                     'model_cfg': {'type': 'FakeModel', 'path': 'fake',
                                   'max_seq_len': 128},
                     'prompts': ['Q: hi\nA:'], 'max_out_len': 4})
        time.sleep(0.2)
        handle.proc.send_signal(signal.SIGTERM)
        resp = read_frame(handle.proc.stdout.fileno(), timeout=60)
        assert resp['ok'] is True and len(resp['completions']) == 1
        handle.proc.wait(timeout=30)
        assert handle.proc.returncode == 0
        assert 'exiting (sigterm)' in open(log).read()
    finally:
        handle.kill()


# -- daemon end-to-end (slow) ----------------------------------------------

def _daemon_env(cache_root):
    env = _worker_env({'OCT_CACHE_ROOT': str(cache_root)})
    env.pop('OCT_TRACE_ID', None)
    env.pop('OCT_OBS_DIR', None)
    return env


def _start_daemon(tmp_path, tag, extra_args=(), env_extra=None):
    """`cli serve` subprocess; returns (proc, base_url, log_path)."""
    log_path = str(tmp_path / f'daemon-{tag}.log')
    log = open(log_path, 'w')
    env = _daemon_env(tmp_path / 'cache')
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'serve',
         DEMO_CFG, '--port', '0', '--idle-ttl', '300',
         '--work-dir', str(tmp_path / 'out'), *extra_args],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=REPO)
    deadline = time.time() + 120
    port = None
    while time.time() < deadline and port is None:
        if proc.poll() is not None:
            raise AssertionError(
                f'daemon died at startup:\n{open(log_path).read()}')
        for line in open(log_path).read().splitlines():
            if 'engine listening on http://127.0.0.1:' in line:
                port = int(line.split('127.0.0.1:')[1].split()[0])
                break
        time.sleep(0.2)
    assert port, f'no listen line:\n{open(log_path).read()}'
    return proc, f'http://127.0.0.1:{port}', log_path


def _wait_ready(base, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            code, rep = _http('GET', base + '/healthz')
            if code == 200:
                return rep
        except (OSError, urllib.error.URLError):
            pass
        time.sleep(0.5)
    raise AssertionError('daemon never became ready')


def _wait_sweep(base, sweep_id, states=('done', 'failed'), timeout=180):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, rep = _http('GET', f'{base}/v1/sweeps/{sweep_id}')
        if code == 200 and rep.get('status') in states:
            return rep
        time.sleep(0.5)
    raise AssertionError(f'sweep {sweep_id} never reached {states}')


def _store_rows(cache_root):
    """Every (key, value) committed to the store's segment files, in
    append order, torn final lines skipped."""
    rows = []
    store = osp.join(str(cache_root), 'store')
    for dirpath, _, files in os.walk(store):
        if osp.basename(dirpath) == 'units':
            continue
        for fname in sorted(files):
            if not fname.endswith('.jsonl'):
                continue
            for line in open(osp.join(dirpath, fname), 'rb'):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and 'k' in rec:
                    rows.append((rec['k'], rec['v']))
    return rows


def _expected_fake_prediction(origin_prompt):
    """FakeModel.generate, replicated: the bit-identity oracle."""
    if 'A:' in origin_prompt:
        return '101'
    digest = hashlib.sha256(origin_prompt.encode()).hexdigest()[:8]
    return f'fake-{digest}'


@pytest.mark.slow
def test_e2e_daemon_two_sweeps_one_build_and_interactive(tmp_path):
    """The headline acceptance: one daemon serves two sweeps enqueued
    back to back with exactly one model build total, answers an
    interactive /v1/completions mid-sweep, honors cancel-while-queued,
    and a repeated completion is a pure store hit."""
    proc, base, log_path = _start_daemon(tmp_path, 'main')
    try:
        ready = _wait_ready(base)
        assert ready['models'] == ['fake-demo']

        code, s1 = _http('POST', base + '/v1/sweeps',
                         {'config_path': DEMO_CFG, 'mode': 'infer'})
        assert code == 202
        code, s2 = _http('POST', base + '/v1/sweeps',
                         {'config_path': DEMO_CFG, 'mode': 'infer',
                          'label': 'second'})
        assert code == 202
        code, s3 = _http('POST', base + '/v1/sweeps',
                         {'config_path': DEMO_CFG, 'mode': 'infer'})
        assert code == 202
        # cancel-while-queued: s3 sits behind two sweeps
        code, rep = _http('DELETE', f'{base}/v1/sweeps/{s3["id"]}')
        assert code == 200 and rep['status'] == 'cancelled'

        # interactive completion while the first sweep runs
        code, comp = _http('POST', base + '/v1/completions',
                           {'model': 'fake-demo',
                            'prompt': 'Q: interactive?\nA:',
                            'max_tokens': 8}, timeout=120)
        assert code == 200
        assert comp['choices'][0]['text'] == '101'
        assert comp['oct']['model_built'] is False   # warm fleet

        rep1 = _wait_sweep(base, s1['id'])
        assert rep1['status'] == 'done'
        assert rep1['detail']['failed_tasks'] == 0
        assert rep1['detail']['queue_wait_seconds'] is not None
        rep2 = _wait_sweep(base, s2['id'])
        assert rep2['status'] == 'done'
        # the identical second sweep was served by the store: the
        # partitioner pruned every task pre-launch
        assert rep2['detail']['n_tasks'] == 0

        code, snap = _http('GET', base + '/status')
        assert code == 200
        serve = snap['serve']
        assert serve['sweeps_done'] == 2
        assert serve['sweeps_cancelled'] == 1
        assert serve['completions'] == 1
        assert serve['workers_resident'] >= 1
        assert serve['worker_reuses'] >= 1

        # exactly ONE model build in the daemon's whole event stream:
        # the warm-up built it; sweep tasks and the interactive request
        # all reused the resident
        events_path = osp.join(serve['run_dir'], 'obs', 'events.jsonl')
        builds = reuses = 0
        for line in open(events_path):
            if '"worker_model_build"' in line:
                builds += 1
            elif '"worker_model_reuse"' in line:
                reuses += 1
        assert builds == 1, f'expected 1 model build, saw {builds}'
        assert reuses >= 2

        # repeated identical completion: zero device rows
        code, comp2 = _http('POST', base + '/v1/completions',
                            {'model': 'fake-demo',
                             'prompt': 'Q: interactive?\nA:',
                             'max_tokens': 8}, timeout=60)
        assert code == 200
        assert comp2['oct']['store_hits'] == 1
        assert comp2['oct']['device_rows'] == 0

        # graceful shutdown
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.slow
def test_e2e_daemon_kill9_restart_resumes(tmp_path):
    """SIGKILL the daemon mid-sweep; a restarted daemon re-claims the
    sweep from the durable queue and converges bit-identically, with
    the store recomputing only the rows the dead daemon never
    committed (no key is ever committed twice)."""
    # stretch the device phase (per-batch injected sleep) so "running
    # with some rows committed, more to come" is a seconds-wide window
    # instead of a race against FakeModel's instant batches
    proc, base, log_path = _start_daemon(
        tmp_path, 'first', env_extra={'OCT_DEBUG_BATCH_SLEEP_S': '0.75'})
    sweep_id = None
    worker_pids = []
    try:
        _wait_ready(base)
        code, rep = _http('POST', base + '/v1/sweeps',
                          {'config_path': DEMO_CFG, 'mode': 'infer'})
        assert code == 202
        sweep_id = rep['id']
        # wait until the sweep is mid-flight with at least one row
        # committed, then pull the plug
        deadline = time.time() + 120
        while time.time() < deadline:
            code, st = _http('GET', f'{base}/v1/sweeps/{sweep_id}')
            if st.get('status') == 'running' \
                    and len(_store_rows(tmp_path / 'cache')) >= 1:
                code, snap = _http('GET', base + '/status')
                worker_pids = [w['pid'] for w in
                               snap['serve']['workers'].values()]
                break
            time.sleep(0.25)
        else:
            raise AssertionError('sweep never got mid-flight')
    finally:
        # kill -9 the daemon AND its resident fleet: an orphaned worker
        # (own session) would otherwise drain the in-flight task on EOF
        # and commit the remaining rows, leaving the restart nothing to
        # recompute
        os.kill(proc.pid, signal.SIGKILL)
        for pid in worker_pids:
            try:
                os.killpg(pid, signal.SIGKILL)   # own session: pid==pgid
            except (OSError, ProcessLookupError):
                pass
        proc.wait()

    rows_before = _store_rows(tmp_path / 'cache')
    assert rows_before, 'kill happened before any commit'
    assert len(rows_before) < 32, 'sweep finished before the kill'
    # belt and braces: wait for the store to go quiescent before the
    # second daemon plans against it
    stable = len(rows_before)
    for _ in range(30):
        time.sleep(1)
        n = len(_store_rows(tmp_path / 'cache'))
        if n == stable:
            break
        stable = n
    rows_before = _store_rows(tmp_path / 'cache')

    proc2, base2, log2 = _start_daemon(tmp_path, 'second')
    try:
        rep = _wait_sweep(base2, sweep_id)
        assert rep['status'] == 'done', open(log2).read()[-2000:]
        rows_after = _store_rows(tmp_path / 'cache')
        keys = [k for k, _ in rows_after]
        # zero duplicate device work: append-only store, every key once
        assert len(keys) == len(set(keys))
        assert len(rows_after) >= len(rows_before)
        # first-daemon rows survived untouched (prefix property)
        assert rows_after[:len(rows_before)] == rows_before \
            or set(dict(rows_before)) <= set(dict(rows_after))

        # bit-identical convergence: every prediction matches the
        # FakeModel oracle recomputed from its own origin prompt
        code, st = _http('GET', f'{base2}/v1/sweeps/{sweep_id}')
        pred_dir = osp.join(st['detail']['work_dir'], 'predictions',
                            'fake-demo')
        pred_files = sorted(os.listdir(pred_dir))
        assert 'demo-gen.json' in pred_files
        gen = json.load(open(osp.join(pred_dir, 'demo-gen.json')))
        assert len(gen) == 16
        for row in gen.values():
            assert row['prediction'] == \
                _expected_fake_prediction(row['origin_prompt'])
        proc2.send_signal(signal.SIGTERM)
        assert proc2.wait(timeout=60) == 0
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait()
