"""Roofline cost model: analytic oracles, timeline/engine threading,
KV-pool pressure counters, and the ledger's --min-mfu-ratio gate.

Unit level: hand-computed FLOPs/bytes for the tiny geometry (prefill
chunk, single decode step, dense vs paged-gather vs ideal, int8-KV and
quantized weight widths), peak-table resolution + env override,
summarize-fold math, allocator high-water/failed-alloc counters, and
the ledger efficiency gate's exit-code matrix.

Wired level (tiny JaxLM, CPU): dense gen batches through run_plan and
engine drains both leave flops/bytes/mfu/mbu on their flight-recorder
records with bytes_kv >= bytes_kv_ideal on the gather path; a starved
page pool emits a structured kv_pool_pressure event; the status fold,
Prometheus gauges, trace-report roofline section, and Perfetto engine
counter tracks all surface the new fields; the Noop/torn paths stay
inert.
"""
import json
import os
import os.path as osp
import subprocess
import sys

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_obs():
    from opencompass_tpu import obs
    obs.reset_obs()
    yield
    obs.reset_obs()


def _tiny_cfg(**kw):
    from opencompass_tpu.nn.config import TransformerConfig
    return TransformerConfig.tiny(**kw)


# -- geometry oracles (hand-computed for the tiny config) -------------------
# tiny: vocab 512, hidden 64, layers 2, heads 4 (head_dim 16), kv_heads
# 2 (kv_dim 32), intermediate 128, gated MLP, dtype float32.
#   per-layer matmuls: qkv 64*(64+2*32)=8192, o 64*64=4096,
#                      mlp 3*64*128=24576  -> 36864
#   total: 2*36864 + lm_head 64*512=32768  -> 106496

def test_matmul_params_oracle():
    from opencompass_tpu.obs import costmodel as cm
    cfg = _tiny_cfg()
    assert cm.matmul_params(cfg) == 106496
    # f32 weights: 4 bytes each
    assert cm.weight_bytes(cfg) == 106496 * 4
    # K+V vectors: 2 * kv_dim(32) * 4B = 256 per token per layer
    assert cm.kv_token_bytes(cfg) == 256.0


def test_quantized_widths_oracle():
    from opencompass_tpu.obs import costmodel as cm
    cfg = _tiny_cfg()
    assert cm.weight_width_bytes(cfg, 'int8') == 1.0
    assert cm.weight_width_bytes(cfg, 'w8a8-kv8') == 1.0
    assert cm.weight_width_bytes(cfg, 'w4a8') == 0.5
    assert cm.weight_width_bytes(cfg) == 4.0  # f32 tiny
    # int8 KV: 2*32 elements at 1B + per-vector scales (one f32 per
    # K/V head pair: 2 heads * 2 tensors * 4B = 16) = 80 B/token/layer
    cfg8 = _tiny_cfg(kv_quant='int8')
    assert cm.kv_token_bytes(cfg8) == 2 * 32 * 1 + 2 * 2 * 4
    # int4 halves the elements, keeps the scales
    cfg4 = _tiny_cfg(kv_quant='int4')
    assert cm.kv_token_bytes(cfg4) == 2 * 32 * 0.5 + 2 * 2 * 4


def test_score_cost_oracle():
    from opencompass_tpu.obs import costmodel as cm
    model = cm.CostModel(_tiny_cfg(), peaks=cm.PeakRates(1e12, 1e11,
                                                        'test'))
    cost = model.score_cost(100, rows=2)
    # matmul: 2 * 106496 * 100; attention pairs: 2 rows of 50 tokens
    # causal = 2 * 50*51/2 = 2550 pairs, 4 * L(2) * q_dim(64) each
    assert cost.flops == 2 * 106496 * 100 + 4 * 2 * 64 * 2550
    assert cost.bytes_w == 106496 * 4
    # K/V written once and read once from HBM: 2 * L * 256 * 100
    assert cost.bytes_kv == 2 * (2 * 256 * 100)
    assert cost.bytes_kv == cost.bytes_kv_ideal  # scoring has no waste
    fields = model.fields(cost, seconds=0.5)
    assert fields['mfu'] == pytest.approx(
        cost.flops / (0.5 * 1e12), abs=1e-6)
    assert fields['mbu'] == pytest.approx(
        (cost.bytes_w + cost.bytes_kv) / (0.5 * 1e11), abs=1e-6)


def test_gen_cost_dense_buffer_vs_ideal():
    from opencompass_tpu.obs import costmodel as cm
    model = cm.CostModel(_tiny_cfg())
    # 4 rows, 25-token prompts, 10 decode steps each, padded cache 160
    cost = model.gen_cost(100, 40, rows=4, cache_width=160)
    # weights stream once for prefill + once per decode step
    assert cost.bytes_w == 106496 * 4 * (1 + 10)
    # ideal reads: prefill once (100) + per decode step each row's
    # ragged length: 4 rows * sum_{t=1..10}(25+t) = 4*305 = 1220
    writes = 2 * 256 * 140
    assert cost.bytes_kv_ideal == writes + 2 * 256 * (100 + 1220)
    # dense buffer reads: 100 + 10 steps * 4 rows * 160 positions
    assert cost.bytes_kv == writes + 2 * 256 * (100 + 6400)
    assert cost.kv_ratio > 1.0
    # without a cache width the dense estimate collapses to ideal
    assert model.gen_cost(100, 40, rows=4).kv_ratio == 1.0


def test_engine_cost_gather_vs_ideal():
    from opencompass_tpu.obs import costmodel as cm
    model = cm.CostModel(_tiny_cfg())
    cost = model.engine_cost(
        prefill_tokens=64, decode_tokens=40, prefill_steps=2,
        decode_steps=10, slots=4, table_positions=256,
        kv_positions=500, attn_positions=1500)
    assert cost.flops == 2 * 106496 * 104 + 4 * 2 * 64 * 1500
    assert cost.bytes_w == 106496 * 4 * 12       # one stream per step
    writes = 2 * 256 * 104
    # gather: every step reads every slot's full table width
    assert cost.bytes_kv == writes + 2 * 256 * (12 * 4 * 256)
    assert cost.bytes_kv_ideal == writes + 2 * 256 * 500
    assert cost.kv_ratio > 1.0


def test_peak_rates_resolution(monkeypatch):
    from opencompass_tpu.obs import costmodel as cm
    monkeypatch.delenv(cm.ENV_PEAK_FLOPS, raising=False)
    monkeypatch.delenv(cm.ENV_PEAK_BYTES, raising=False)
    assert cm.peak_rates('tpu', 'TPU v4').flops_per_s == 275e12
    # longest-prefix: v5 lite must not resolve as v5
    assert cm.peak_rates('tpu', 'TPU v5 lite').source == 'TPU v5 lite'
    assert cm.peak_rates('gpu', 'NVIDIA H100 80GB').source == 'H100'
    assert cm.peak_rates('cpu', None).source == 'cpu'
    # the CI-determinism override beats detection
    monkeypatch.setenv(cm.ENV_PEAK_FLOPS, '1e12')
    monkeypatch.setenv(cm.ENV_PEAK_BYTES, '1e11')
    peaks = cm.peak_rates('tpu', 'TPU v4')
    assert peaks.source == 'env' and peaks.bytes_per_s == 1e11


def test_cost_model_for_model_none_without_geometry():
    from opencompass_tpu.models import FakeModel
    from opencompass_tpu.obs.costmodel import CostModel
    assert CostModel.for_model(FakeModel(path='fake')) is None
    assert CostModel.for_model(object()) is None


# -- allocator pressure counters --------------------------------------------

def test_page_allocator_high_water_and_failed_allocs():
    from opencompass_tpu.nn.paged_kv import OutOfPages, PageAllocator
    alloc = PageAllocator(8)           # 7 usable (page 0 reserved)
    a = alloc.alloc(3)
    b = alloc.alloc(2)
    assert alloc.high_water == 5
    alloc.free(b)
    assert alloc.high_water == 5       # high-water survives frees
    with pytest.raises(OutOfPages):
        alloc.alloc(5)                 # only 4 free
    assert alloc.failed_allocs == 1
    stats = alloc.stats()
    assert stats['used'] == 3 and stats['high_water'] == 5
    assert stats['used_frac'] == pytest.approx(3 / 7, abs=1e-4)
    assert stats['high_water_frac'] == pytest.approx(5 / 7, abs=1e-4)
    assert stats['failed_allocs'] == 1
    alloc.free(a)
    assert alloc.n_free == 7


# -- summarize fold ----------------------------------------------------------

def test_summarize_folds_cost_fields():
    from opencompass_tpu.obs.timeline import summarize_records
    records = [
        {'t': 'batch', 'ts': 0.0, 'kind': 'gen', 'batch_s': 1.0,
         'device_s': 1.0, 'rows': 2, 'flops': 100, 'bytes_w': 10,
         'bytes_kv': 40, 'bytes_kv_ideal': 20, 'mfu': 0.4,
         'mbu': 0.2},
        {'t': 'engine', 'ts': 1.0, 'kind': 'gen', 'decode_steps': 4,
         'slot_util': 1.0, 'device_seconds': 3.0, 'retired': 2,
         'flops': 300, 'bytes_w': 30, 'bytes_kv': 60,
         'bytes_kv_ideal': 30, 'mfu': 0.8, 'mbu': 0.6},
    ]
    s = summarize_records(records)
    assert s['flops'] == 400 and s['bytes_w'] == 40
    assert s['bytes_kv'] == 100 and s['bytes_kv_ideal'] == 50
    assert s['kv_ratio'] == pytest.approx(2.0)
    # weighted by device wall: (0.4*1 + 0.8*3) / 4
    assert s['mfu'] == pytest.approx(0.7)
    assert s['mbu'] == pytest.approx(0.5)
    # records without cost fields leave the summary keys None
    bare = summarize_records([{'t': 'batch', 'ts': 0.0, 'kind': 'ppl',
                               'batch_s': 0.1}])
    assert bare['mfu'] is None and bare['kv_ratio'] is None


# -- wired: dense batches + engine drains carry cost fields ------------------

def _tiny_lm(**kw):
    from opencompass_tpu.models.jax_lm import JaxLM
    return JaxLM(config='tiny', max_seq_len=128, **kw)


def test_dense_gen_batches_record_cost_fields(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.icl.inferencers.gen import GenInferencer
    from opencompass_tpu.obs import timeline as tmod
    obs.init_obs(str(tmp_path))
    obs.init_task_timeline('dense-cost')
    lm = _tiny_lm()
    inf = GenInferencer(model=lm, max_out_len=8, batch_size=4,
                        batch_plan=True)
    prompts = ['alpha beta', 'gamma delta epsilon', 'zeta', 'eta theta']
    lengths = [lm.get_token_len(p) for p in prompts]
    plan = inf.make_plan(lengths, seq_cap=120)
    inf.run_plan(
        plan,
        lambda b: lm.generate_async([prompts[i] for i in b.indices], 8),
        lambda b, r: None, kind='gen')
    (records,) = tmod.read_timelines(
        osp.join(str(tmp_path), 'obs')).values()
    batches = [r for r in records if r['t'] == 'batch']
    assert batches
    for b in batches:
        assert b['flops'] > 0 and b['bytes_w'] > 0
        # dense decode reads the padded buffer: actual >= ideal
        assert b['bytes_kv'] >= b['bytes_kv_ideal'] > 0
        assert 0 < b['mfu'] < 1 and 0 < b['mbu'] < 1
    summary = tmod.summarize_records(records)
    assert summary['mfu'] and summary['mbu']
    assert summary['kv_ratio'] >= 1.0


def test_scoring_batches_record_cost_fields(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.icl.inferencers.base import BaseInferencer
    from opencompass_tpu.obs import timeline as tmod
    obs.init_obs(str(tmp_path))
    obs.init_task_timeline('score-cost')
    lm = _tiny_lm()
    inf = BaseInferencer(model=lm, batch_size=4, batch_plan=True)
    prompts = ['one two three', 'four five', 'six']
    plan = inf.make_plan([lm.get_token_len(p) for p in prompts])
    inf.run_plan(
        plan,
        lambda b: lm.get_ppl_async([prompts[i] for i in b.indices]),
        lambda b, r: None, kind='ppl')
    (records,) = tmod.read_timelines(
        osp.join(str(tmp_path), 'obs')).values()
    batches = [r for r in records if r['t'] == 'batch']
    assert batches
    for b in batches:
        # scoring has no decode buffer waste: actual == ideal
        assert b['bytes_kv'] == b['bytes_kv_ideal'] > 0
        assert b['mfu'] > 0


def test_engine_drain_records_cost_and_pool(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.obs import timeline as tmod
    obs.init_obs(str(tmp_path))
    obs.init_task_timeline('engine-cost')
    lm = _tiny_lm(continuous_batching=True, decode_slots=2,
                  kv_page_size=16)
    outs = lm.generate_continuous(
        ['the quick brown fox', 'jumps over'], 8)
    assert len(outs) == 2
    (records,) = tmod.read_timelines(
        osp.join(str(tmp_path), 'obs')).values()
    (eng,) = [r for r in records if r['t'] == 'engine']
    assert eng['flops'] > 0 and eng['bytes_w'] > 0
    # XLA paged-gather reads the full table width every step: the
    # actual-vs-ideal ratio is the ROADMAP-item-1 waste number, > 1
    assert eng['bytes_kv'] > eng['bytes_kv_ideal'] > 0
    assert eng['mfu'] > 0 and eng['mbu'] > 0
    assert eng['dur_s'] > 0
    assert eng['kv_positions'] > 0
    assert eng['attn_positions'] >= eng['kv_positions']
    pool = eng['kv_pool']
    assert pool['high_water'] > 0 and pool['failed_allocs'] == 0
    assert pool['used'] == 0            # all rows retired: pages freed


def test_kv_pool_pressure_event(tmp_path):
    """A pool too small for the queued rows bounces admissions — the
    allocator counts them and a structured kv_pool_pressure event
    lands in the run's event stream."""
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path))
    # pool of 5 (4 usable) pages; each row needs 2 pages -> only two
    # rows resident at once, the rest queue (back-pressure)
    lm = _tiny_lm(continuous_batching=True, decode_slots=4,
                  kv_page_size=16, kv_pool_pages=5)
    outs = lm.generate_continuous(
        ['aa bb cc', 'dd ee ff', 'gg hh ii', 'jj kk ll'], 8)
    assert all(isinstance(t, str) for t in outs)
    engine = lm.continuous_engine()
    assert engine.alloc.failed_allocs > 0
    assert engine.alloc.n_allocated == 0     # drained clean
    tracer.close()
    events = [json.loads(line) for line in
              open(osp.join(str(tmp_path), 'obs', 'events.jsonl'))
              if line.strip()]
    pressure = [e for e in events
                if e.get('name') == 'kv_pool_pressure']
    assert pressure, 'admission stall left no kv_pool_pressure event'
    attrs = pressure[0]['attrs']
    assert attrs['need_pages'] >= 1 and attrs['pool_pages'] == 5
    assert attrs['queued_rows'] >= 1


def test_noop_timeline_skips_cost_work(tmp_path):
    """With no timeline installed the cost path never runs and no
    files appear (the disabled-path contract)."""
    from opencompass_tpu.icl.inferencers.base import BaseInferencer
    lm = _tiny_lm()
    inf = BaseInferencer(model=lm, batch_size=2, batch_plan=True)
    plan = inf.make_plan([3, 4])
    inf.run_plan(
        plan,
        lambda b: lm.get_ppl_async(['x y z', 'p q r s'][:len(
            b.indices)]),
        lambda b, r: None, kind='ppl')
    assert os.listdir(str(tmp_path)) == []


def test_torn_cost_record_recovery(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.obs import timeline as tmod
    obs.init_obs(str(tmp_path))
    tl = obs.init_task_timeline('torn-cost')
    tl.batch('gen', ts=1.0, shape=[2, 8], rows=2, real_tokens=10,
             pad_tokens=6, batch_s=0.1, device_s=0.1, flops=1000,
             bytes_w=100, bytes_kv=50, bytes_kv_ideal=25, mfu=0.1,
             mbu=0.2)
    with open(tl.path, 'a', encoding='utf-8') as f:
        f.write('{"v":1,"t":"engine","ts":2.0,"flops":12')
    records = list(tmod.iter_records(tl.path))
    assert len(records) == 1
    s = tmod.summarize_records(records)
    assert s['flops'] == 1000 and s['kv_ratio'] == 2.0


# -- status fold / prometheus / report / export ------------------------------

def test_status_fold_and_prom_gauges():
    from opencompass_tpu.obs.live import fold_task_rows
    from opencompass_tpu.obs.promexport import render_prometheus
    tasks = {
        'a': {'state': 'running', 'progress': 0.5, 'mfu': 0.2,
              'mbu': 0.4, 'kv_pool_used_frac': 0.3,
              'kv_pool_high_water_frac': 0.6,
              'kv_pool_failed_allocs': 2, 'decode_slot_util': 0.9},
        'b': {'state': 'running', 'progress': 0.5, 'mfu': 0.4,
              'mbu': 0.6, 'kv_pool_used_frac': 0.1,
              'kv_pool_high_water_frac': 0.2},
    }
    overall = fold_task_rows(tasks)
    assert overall['mfu'] == pytest.approx(0.3)
    assert overall['mbu'] == pytest.approx(0.5)
    # pool gauges fold pessimistically (worst task) + stall total
    assert overall['kv_pool_used_frac'] == pytest.approx(0.3)
    assert overall['kv_pool_high_water_frac'] == pytest.approx(0.6)
    assert overall['kv_pool_failed_allocs'] == 2
    text = render_prometheus({}, status={'overall': overall,
                                         'tasks': tasks})
    assert 'oct_run_mfu 0.3' in text
    assert 'oct_run_mbu 0.5' in text
    assert 'oct_kv_pool_used_frac 0.3' in text
    assert 'oct_kv_pool_failed_allocs 2' in text
    assert 'oct_task_mbu{task="a"} 0.4' in text
    assert 'oct_task_mfu{task="b"} 0.4' in text


def test_trace_report_roofline_section(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.obs.report import build_report, render_report
    tracer = obs.init_obs(str(tmp_path))
    with tracer.span('run'):
        tl = obs.init_task_timeline('roof-task')
        tl.set_unit('m/d')
        tl.plan('gen', stats={}, planned=True)
        tl.batch('gen', ts=1.0, shape=[2, 16], rows=2, real_tokens=20,
                 pad_tokens=12, batch_s=0.5, device_s=0.4,
                 tokens_in=20, tokens_out=8, flops=5000, bytes_w=400,
                 bytes_kv=200, bytes_kv_ideal=100, mfu=0.12, mbu=0.34)
    tracer.close()
    report = build_report(str(tmp_path))
    text = render_report(report)
    assert 'roofline (MFU/MBU attribution)' in text
    assert '12.0%' in text and '34.0%' in text   # mfu/mbu columns
    assert '2.00x' in text                       # kv_ratio column
    assert 'KV read traffic runs 2.00x' in text
    # summary line rides render_summary
    assert 'roofline:' in text


def test_perfetto_export_engine_counter_tracks(tmp_path):
    from opencompass_tpu import obs
    from opencompass_tpu.obs.export import build_chrome_trace
    tracer = obs.init_obs(str(tmp_path))
    with tracer.span('run'):
        tl = obs.init_task_timeline('eng-task')
        tl.plan('gen', stats={}, planned=True)
        tl.engine('gen', ts=10.0, dur_s=2.0, rows=3, slots=4,
                  page_size=16, steps=12, prefill_steps=2,
                  decode_steps=10, joined=3, retired=3, slot_util=0.75,
                  occupancy_series=[3, 4, 2], flops=9000, bytes_w=800,
                  bytes_kv=600, bytes_kv_ideal=200, mfu=0.11, mbu=0.22)
    tracer.close()
    doc = build_chrome_trace(str(tmp_path))
    events = doc['traceEvents']
    drains = [e for e in events if e.get('cat') == 'engine'
              and e['ph'] == 'X']
    assert drains and drains[0]['args']['mbu'] == 0.22
    counters = [e for e in events if e.get('cat') == 'engine'
                and e['ph'] == 'C']
    occ = [e for e in counters if e['name'].startswith('slots ')]
    assert [e['args']['occupied'] for e in occ] == [3, 4, 2]
    # monotone: occupancy samples spread across the drain interval
    assert [e['ts'] for e in occ] == sorted(e['ts'] for e in occ)
    assert any(e['name'].startswith('mfu ') for e in counters)
    assert any(e['name'].startswith('mbu ') for e in counters)
    # well-formedness is preserved: every B still has its E per track
    by_track = {}
    for e in events:
        if e['ph'] in ('B', 'E'):
            by_track.setdefault((e['pid'], e.get('tid')),
                                []).append(e['ph'])
    for phs in by_track.values():
        depth = 0
        for ph in phs:
            depth += 1 if ph == 'B' else -1
            assert depth >= 0
        assert depth == 0


# -- serve plane: per-request MBU --------------------------------------------

def test_request_record_carries_forward_phase_mbu(tmp_path):
    """The daemon lays the worker's forward-phase MFU/MBU onto the
    model_forward child span of the requests.jsonl record, and the
    rolling /v1/stats window folds a per-model mbu_mean."""
    import time

    from opencompass_tpu.obs import reqtrace
    from opencompass_tpu.serve.daemon import EvalEngine
    obs_root = str(tmp_path)
    eng = EvalEngine.__new__(EvalEngine)
    eng.req_recorder = reqtrace.RequestRecorder(obs_root)
    eng.req_stats = reqtrace.RollingStats()
    eng._catalog = {'m': {}}
    eng.tracer = None
    eng._record_request(
        response_id='cmpl-x', request_id='req-1', ts=time.time(),
        model='m', wall_s=0.5, parse_s=0.001,
        timings={'lease_wait_s': 0.01, 'roundtrip_s': 0.3},
        resp={'phases': {'model_forward_s': 0.2,
                         'store_lookup_s': 0.01},
              'mbu': 0.42, 'mfu': 0.1, 'ttft_s': 0.05,
              'store_hits': 0, 'device_rows': 1,
              'prompt_tokens': 10, 'completion_tokens': 8},
        error=None)
    (rec,) = reqtrace.iter_requests(
        osp.join(obs_root, reqtrace.REQUESTS_FILE))
    forward = [p for p in rec['phases']
               if p['name'] == 'model_forward']
    assert forward and forward[0]['mbu'] == 0.42
    assert forward[0]['mfu'] == 0.1
    # no other phase carries the fields
    assert all('mbu' not in p for p in rec['phases']
               if p['name'] != 'model_forward')
    summary = eng.req_stats.summary(window_s=60)
    assert summary['completions']['per_model']['m']['mbu_mean'] \
        == pytest.approx(0.42)


def test_rolling_stats_mbu_mean_mixed_samples():
    from opencompass_tpu.obs.reqtrace import RollingStats
    rs = RollingStats()
    rs.record_completion('m', 0.1, mbu=0.5)
    rs.record_completion('m', 0.2, mbu=0.3)
    rs.record_completion('m', 0.3)          # store-served: no mbu
    row = rs.summary(window_s=60)['completions']['per_model']['m']
    assert row['mbu_mean'] == pytest.approx(0.4)
    assert row['count'] == 3


# -- ledger efficiency gate ---------------------------------------------------

def _ledger(tmp_path, rows):
    from opencompass_tpu.utils.fileio import append_jsonl_atomic
    led = tmp_path / 'ledger'
    led.mkdir(parents=True, exist_ok=True)
    append_jsonl_atomic(str(led / 'runs.jsonl'), rows)
    return str(led)


def _rec(run, mfu=None, tps=100.0, model='m', dataset='d', acc=80.0):
    rec = {'v': 1, 'ts': 1.0, 'run': run, 'model': model,
           'dataset': dataset, 'kind': 'gen', 'tokens_per_sec': tps,
           'samples_per_sec': tps / 10, 'wall_seconds': 1.0,
           'compile_seconds': 0.1, 'pad_eff': 0.9,
           'accuracy': {'score': acc}}
    if mfu is not None:
        rec['mfu'] = mfu
        rec['mbu'] = mfu * 2
    return rec


def test_check_records_min_mfu_ratio():
    from opencompass_tpu.ledger import check_records
    records = [_rec('r1', mfu=0.40), _rec('r2', mfu=0.15)]
    # off by default: tokens/s identical -> no regression
    assert check_records(records, 'r1', 'r2') == []
    regs = check_records(records, 'r1', 'r2', min_mfu_ratio=0.5)
    assert len(regs) == 1 and regs[0]['regression'] == 'efficiency'
    assert regs[0]['mfu'] == 0.15 and regs[0]['mfu_base'] == 0.40
    # identical rerun passes
    assert check_records([_rec('r1', mfu=0.4), _rec('r3', mfu=0.4)],
                         'r1', 'r3', min_mfu_ratio=0.5) == []
    # rows without an MFU on either side are skipped, not failed
    assert check_records([_rec('r1'), _rec('r2', mfu=0.1)],
                         'r1', 'r2', min_mfu_ratio=0.5) == []
    assert check_records([_rec('r1', mfu=0.4), _rec('r2')],
                         'r1', 'r2', min_mfu_ratio=0.5) == []
    # a fully store-served side skips the gate like the throughput one
    cached = dict(_rec('r2', mfu=0.01, tps=0.0), store_hit_rate=1.0)
    assert check_records([_rec('r1', mfu=0.4), cached],
                         'r1', 'r2', min_mfu_ratio=0.5) == []


def test_ledger_cli_min_mfu_ratio_exit_codes(tmp_path):
    led = _ledger(tmp_path, [_rec('r1', mfu=0.40),
                             _rec('r2', mfu=0.15)])

    def cli(*argv):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        return subprocess.run(
            [sys.executable, '-m', 'opencompass_tpu.cli', 'ledger',
             *argv], cwd=REPO, env=env, capture_output=True,
            text=True, timeout=120)

    # throughput unchanged: plain check passes
    assert cli('check', '--ledger', led).returncode == 0
    # the efficiency gate trips on the halved MFU
    r = cli('check', '--ledger', led, '--min-mfu-ratio', '0.5')
    assert r.returncode == 2, r.stdout + r.stderr
    assert 'MFU' in r.stdout
    # an identical rerun passes the same gate
    led2 = _ledger(tmp_path / 'b', [_rec('r1', mfu=0.40),
                                    _rec('r2', mfu=0.40)])
    r = cli('check', '--ledger', led2, '--min-mfu-ratio', '0.5')
    assert r.returncode == 0, r.stdout + r.stderr
    # json mode carries the regression row
    r = cli('check', '--ledger', led, '--min-mfu-ratio', '0.5',
            '--json')
    assert r.returncode == 2
    payload = json.loads(r.stdout)
    assert payload['regressions'][0]['regression'] == 'efficiency'


def test_collect_run_records_joins_roofline(tmp_path):
    """Ledger records pick up mfu/mbu/kv_ratio from the run's timeline
    summaries (the check gate's data source)."""
    from opencompass_tpu import obs
    from opencompass_tpu.ledger import collect_run_records
    work = tmp_path / 'run'
    (work / 'perf' / 'm').mkdir(parents=True)
    json.dump({'wall_seconds': 1.0, 'tokens_per_sec': 10.0,
               'samples': 2}, open(work / 'perf' / 'm' / 'd.json', 'w'))
    obs.init_obs(str(work))
    tl = obs.init_task_timeline('t')
    tl.set_unit('m/d')
    tl.plan('gen', stats={}, planned=True)
    tl.batch('gen', ts=1.0, shape=[1, 8], rows=1, real_tokens=8,
             pad_tokens=0, batch_s=0.2, device_s=0.2, tokens_in=8,
             flops=100, bytes_w=10, bytes_kv=40, bytes_kv_ideal=20,
             mfu=0.25, mbu=0.5)
    obs.reset_obs()
    (rec,) = collect_run_records(str(work), run_id='rX')
    assert rec['mfu'] == pytest.approx(0.25)
    assert rec['mbu'] == pytest.approx(0.5)
    assert rec['kv_ratio'] == pytest.approx(2.0)
