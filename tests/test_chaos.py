"""Serve-layer chaos harness (analysis/chaos.py): invariant checkers
as pure units, the quick scenario profile live against a real daemon
(tier-1), the CLI exit-code convention, and the full kill-sweep (slow
tier)."""
import json
import os
import os.path as osp
import subprocess
import sys

import pytest

from opencompass_tpu.analysis import chaos

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


# -- invariant checkers (pure) ----------------------------------------------

def _access(rid, status, route='/v1/completions', method='POST'):
    return {'v': 1, 'ts': 1000.0, 'method': method, 'path': route,
            'route': route, 'status': status, 'request_id': rid}


def test_check_no_lost_requests():
    access = [_access('req-a', 200), _access('req-b', 503),
              _access('req-c', 429), _access('req-d', 400),
              _access('req-e', 404),
              _access('req-z', 200, route='/healthz', method='GET')]
    requests = [{'request_id': 'req-a', 'status': 'ok'},
                {'request_id': 'req-b', 'status': 'error'},
                {'request_id': 'req-c', 'status': 'error'}]
    # 400/404 never reach the engine; everything else resolved
    assert chaos.check_no_lost_requests(access, requests) == []
    # a 200 without a requests.jsonl record is a silent loss
    access.append(_access('req-lost', 200))
    violations = chaos.check_no_lost_requests(access, requests)
    assert len(violations) == 1 and 'req-lost' in violations[0]
    # ...and so is an admitted 5xx
    access[-1] = _access('req-lost2', 502)
    violations = chaos.check_no_lost_requests(access, requests)
    assert len(violations) == 1 and 'req-lost2' in violations[0]


def _resp(code, retry_after=None, err_type='overloaded'):
    headers = {}
    if retry_after is not None:
        headers['Retry-After'] = str(retry_after)
    return chaos._Resp(code, {'error': {'type': err_type}}, headers,
                       0.01)


def test_check_retry_after():
    assert chaos.check_retry_after(
        [_resp(200), _resp(429, 5), _resp(503, 1)]) == []
    violations = chaos.check_retry_after([_resp(429)])
    assert violations and 'Retry-After' in violations[0]
    violations = chaos.check_retry_after(
        [_resp(429, 5, err_type='server_error')])
    assert violations and 'overloaded' in violations[0]
    # Retry-After of 0 invites an immediate hammer: a violation
    assert chaos.check_retry_after([_resp(503, 0)])


def test_admitted_p99():
    responses = [chaos._Resp(200, {}, {}, w)
                 for w in (0.1, 0.2, 0.3)]
    responses.append(chaos._Resp(429, {}, {}, 9.9))  # sheds excluded
    assert chaos.admitted_p99_ms(responses) == 300.0
    assert chaos.admitted_p99_ms([chaos._Resp(429, {}, {}, 1)]) is None


def test_run_chaos_rejects_unknown_scenario(tmp_path):
    with pytest.raises(ValueError):
        chaos.run_chaos(['no_such_fault'], workdir=str(tmp_path))


# -- CLI exit-code convention -----------------------------------------------

def test_cli_check_exit_codes(monkeypatch, capsys):
    from opencompass_tpu.analysis.chaos import main

    def boom(*a, **kw):
        raise AssertionError('invariant X violated')

    monkeypatch.setattr(chaos, 'run_chaos', boom)
    assert main(['--check']) == 2            # the ledger-check convention
    assert main([]) == 1                     # visible failure without it
    monkeypatch.setattr(
        chaos, 'run_chaos',
        lambda *a, **kw: {'v': 1, 'quick': True, 'scenarios': {},
                          'requests_checked': 0, 'wall_s': 0.0})
    assert main(['--check', '--json']) == 0
    assert json.loads(capsys.readouterr().out)['v'] == 1


# -- live: the tier-1 quick profile -----------------------------------------

def test_quick_scenarios_live(tmp_path):
    """The tier-1 chaos gate: overload shedding + stuck-worker
    deadlines against one real daemon, every invariant asserted inside
    run_chaos (a returned report IS the all-clear)."""
    report = chaos.run_chaos(list(chaos.QUICK_SCENARIOS),
                             workdir=str(tmp_path / 'chaos'),
                             quick=True)
    assert set(report['scenarios']) == set(chaos.QUICK_SCENARIOS)
    burst = report['scenarios']['overload_burst']
    assert burst['admitted'] >= 1 and burst['shed'] >= 1
    assert burst['admitted_p99_ms'] <= chaos.OBJECTIVE_MS
    assert report['requests_checked'] >= burst['fired']


def test_flaky_api_scenario_daemonless(tmp_path):
    """The outbound resilience gate (`cli chaos --scenario flaky_api
    --check`): 429 pacing adaptation with budgeted retries, breaker
    open → half-open probe → close, deadline-bounded stall, and
    bit-identical partial-failure resume — all against the in-process
    stub provider, no daemon spawned."""
    report = chaos.run_chaos(['flaky_api'],
                             workdir=str(tmp_path / 'chaos'),
                             quick=True)
    assert set(report['scenarios']) == {'flaky_api'}
    flaky = report['scenarios']['flaky_api']
    assert flaky['burst']['http_429'] >= 1
    assert flaky['burst']['limit_low_water'] < 6
    assert flaky['breaker']['closed_by_probe'] is True
    assert flaky['stall']['kind'] in ('deadline_exceeded', 'stall')
    assert flaky['partial']['resume_converged'] is True
    # daemonless: the access-log invariant had nothing to check
    assert report['requests_checked'] == 0


# -- live: the full kill-sweep (slow) ---------------------------------------

@pytest.mark.slow
def test_full_chaos_sweep_cli(tmp_path):
    """`cli chaos --check` end to end: all four scenarios (worker
    SIGKILL + breaker lifecycle included) exit 0 on a healthy build."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'chaos',
         '--check', '--json', '--workdir', str(tmp_path / 'chaos')],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=500)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert set(report['scenarios']) == set(chaos.SCENARIOS)
    kill = report['scenarios']['worker_kill']
    assert kill['breaker_closed'] is True
    assert report['scenarios']['store_eio']['converged'] is True
