"""APITemplateParser semantics (mirrors reference
tests/prompt/test_api_template_parser.py): chat-message conversion,
same-role merging, raw-string drops, gen-mode truncation."""
import warnings

from opencompass_tpu.models import APITemplateParser
from opencompass_tpu.utils.prompt import PromptList

META = dict(round=[
    dict(role='HUMAN', api_role='user'),
    dict(role='BOT', api_role='assistant', generate=True),
])


def _round_pl():
    return PromptList([
        dict(section='round', pos='begin'),
        dict(role='HUMAN', prompt='q0'),
        dict(role='BOT', prompt='a0'),
        dict(role='HUMAN', prompt='q1'),
        dict(role='BOT', prompt=''),
        dict(section='round', pos='end'),
    ])


def test_messages_and_gen_truncation():
    parser = APITemplateParser(META)
    out = parser.parse_template(_round_pl(), mode='gen')
    assert [m['role'] for m in out] == ['user', 'assistant', 'user']
    assert [m['prompt'] for m in out] == ['q0', 'a0', 'q1']


def test_ppl_mode_keeps_final_answer():
    parser = APITemplateParser(META)
    out = parser.parse_template(_round_pl(), mode='ppl')
    assert [m['role'] for m in out] == \
        ['user', 'assistant', 'user', 'assistant']


def test_same_role_merge():
    meta = dict(round=[
        dict(role='HUMAN', api_role='user'),
        dict(role='BOT', api_role='assistant', generate=True),
    ], reserved_roles=[dict(role='SYSTEM', api_role='user')])
    parser = APITemplateParser(meta)
    pl = PromptList([
        dict(section='begin', pos='begin'),
        dict(role='SYSTEM', prompt='sys'),
        dict(section='begin', pos='end'),
        dict(section='round', pos='begin'),
        dict(role='HUMAN', prompt='q'),
        dict(role='BOT', prompt=''),
        dict(section='round', pos='end'),
    ])
    out = parser.parse_template(pl, mode='gen')
    # SYSTEM(api user) merges with HUMAN(api user)
    assert len(out) == 1
    assert out[0] == {'role': 'user', 'prompt': 'sys\nq'}


def test_raw_string_dropped_with_warning():
    parser = APITemplateParser(META)
    pl = PromptList([
        'stray text',
        dict(section='round', pos='begin'),
        dict(role='HUMAN', prompt='q'),
        dict(role='BOT', prompt=''),
        dict(section='round', pos='end'),
    ])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        out = parser.parse_template(pl, mode='gen')
    assert any('ignored' in str(x.message) or 'dropped' in str(x.message)
               for x in w)
    assert [m['prompt'] for m in out] == ['q']


def test_no_meta_template_flattens():
    parser = APITemplateParser(None)
    pl = PromptList([dict(role='HUMAN', prompt='q'),
                     dict(role='BOT', prompt='a')])
    assert parser.parse_template(pl, mode='ppl') == 'q\na'


def test_str_passthrough():
    parser = APITemplateParser(META)
    assert parser.parse_template('plain', mode='gen') == 'plain'
