"""Numerics for the Pallas fused int4-dequant matmul
(nn/int4_matmul.py), via the Pallas interpreter on CPU.  The kernel is
the compute core for the (in-progress) stacked-weight decode path; its
contract is closeness to the dequantized reference product under the
int4x2 storage scheme (quant._pack_int4x2)."""
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.nn import int4_matmul as im
from opencompass_tpu.nn.quant import GROUP, _pack_int4x2


def _dequant(packed, scales):
    lo = (packed & 0xF).astype(np.int8)
    lo = np.where(lo > 7, lo - 16, lo)
    hi = (packed >> 4).astype(np.int8)
    hi = np.where(hi > 7, hi - 16, hi)
    w8 = np.concatenate([lo, hi], -1).astype(np.float32)
    O, K = w8.shape
    s = np.asarray(scales.astype(jnp.float32))
    return (w8.reshape(O, K // GROUP, GROUP) * s[..., None]).reshape(O, K)


@pytest.mark.parametrize('M,O,K', [
    (8, 256, 256),        # minimal aligned shapes
    (5, 384, 512),        # M needs sublane padding
    (32, 256, 768),       # multiple groups per row
])
def test_packed_matmul_matches_dequant_reference(M, O, K):
    rs = np.random.RandomState(0)
    w = rs.randn(K, O).astype(np.float32) * 0.05
    packed, s = _pack_int4x2(w, -2, np)          # NT: (O, K/2), (O, K/G)
    x = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
    sp = jnp.asarray(s, jnp.bfloat16)
    y = im.packed_matmul(x, jnp.asarray(packed), sp, interpret=True)
    ref = np.asarray(x, np.float32) @ _dequant(
        packed, jnp.asarray(s, jnp.bfloat16)).T
    err = np.abs(np.asarray(y, np.float32) - ref).max()
    assert err < 0.02 * max(1.0, np.abs(ref).max())


def test_supported_gates():
    bf16 = jnp.bfloat16
    # interpret=True bypasses the platform gate so the shape/dtype
    # logic is actually exercised on the CPU suite
    assert im.supported(8, 256, 256, bf16, interpret=True)
    assert not im.supported(8, 256, 250, bf16, interpret=True)   # K align
    assert not im.supported(2048, 256, 256, bf16, interpret=True)
    assert not im.supported(8, 256, 256, jnp.float32, interpret=True)
    # TPU gate: this suite runs on CPU, so even good shapes are gated
    assert not im.supported(8, 256, 256, bf16)


def test_stacked_matches_flat():
    from opencompass_tpu.nn.quant import _pack_int4x2
    import jax.numpy as jnp
    rs = np.random.RandomState(1)
    L, M, O, K = 3, 8, 256, 512
    packs, scales = [], []
    for layer in range(L):
        w = rs.randn(K, O).astype(np.float32) * 0.05
        pw, s = _pack_int4x2(w, -2, np)
        packs.append(pw)
        scales.append(s)
    wst = jnp.asarray(np.stack(packs))
    sst = jnp.asarray(np.stack(scales), jnp.bfloat16)
    x = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
    for layer in range(L):
        flat = im.packed_matmul(x, wst[layer], sst[layer], interpret=True)
        stacked = im.packed_matmul_stacked(x, wst, sst, jnp.int32(layer),
                                           interpret=True)
        assert np.array_equal(np.asarray(flat, np.float32),
                              np.asarray(stacked, np.float32))


@pytest.mark.parametrize('remat', [False, True])
def test_full_w4_decode_path(monkeypatch, remat):
    """End-to-end packed-weight decode through _stack's kernel path
    (stacked-weight matmuls + decode-attention kernel, interpreted)
    agrees with the XLA packed path."""
    import dataclasses
    import functools
    import jax
    import opencompass_tpu.nn.decode_attention as DA
    from opencompass_tpu.nn import TransformerConfig
    from opencompass_tpu.nn.decode import greedy_generate
    from opencompass_tpu.nn.quant import init_packed_params

    cfg = dataclasses.replace(
        TransformerConfig.llama(
            vocab_size=97, hidden_size=256, num_layers=2, num_heads=2,
            num_kv_heads=2, intermediate_size=512, max_seq_len=128),
        kv_quant='int8', remat=remat)  # remat flattens _StackedPacked
    params = init_packed_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(1, 97, (2, 10)), jnp.int32)
    mask = jnp.ones_like(tokens, jnp.bool_)
    gen = jax.jit(functools.partial(
        greedy_generate, cfg=cfg, max_new_tokens=5, eos_token_id=None))
    ref = np.asarray(gen(params, tokens=tokens, pad_mask=mask)[0])
    monkeypatch.setattr(DA, 'FORCE_INTERPRET', True)
    monkeypatch.setattr(im, 'FORCE_INTERPRET', True)
    jax.clear_caches()
    out = np.asarray(gen(params, tokens=tokens, pad_mask=mask)[0])
    agree = (ref == out).mean()
    assert agree >= 0.8, (ref, out)
