"""Numerics for the Pallas fused int4-dequant matmul
(nn/int4_matmul.py), via the Pallas interpreter on CPU.  The kernel is
the compute core for the (in-progress) stacked-weight decode path; its
contract is closeness to the dequantized reference product under the
int4x2 storage scheme (quant._pack_int4x2)."""
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.nn import int4_matmul as im
from opencompass_tpu.nn.quant import GROUP, _pack_int4x2


def _dequant(packed, scales):
    lo = (packed & 0xF).astype(np.int8)
    lo = np.where(lo > 7, lo - 16, lo)
    hi = (packed >> 4).astype(np.int8)
    hi = np.where(hi > 7, hi - 16, hi)
    w8 = np.concatenate([lo, hi], -1).astype(np.float32)
    O, K = w8.shape
    s = np.asarray(scales.astype(jnp.float32))
    return (w8.reshape(O, K // GROUP, GROUP) * s[..., None]).reshape(O, K)


@pytest.mark.parametrize('M,O,K', [
    (8, 256, 256),        # minimal aligned shapes
    (5, 384, 512),        # M needs sublane padding
    (32, 256, 768),       # multiple groups per row
])
def test_packed_matmul_matches_dequant_reference(M, O, K):
    rs = np.random.RandomState(0)
    w = rs.randn(K, O).astype(np.float32) * 0.05
    packed, s = _pack_int4x2(w, -2, np)          # NT: (O, K/2), (O, K/G)
    x = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
    sp = jnp.asarray(s, jnp.bfloat16)
    y = im.packed_matmul(x, jnp.asarray(packed), sp, interpret=True)
    ref = np.asarray(x, np.float32) @ _dequant(
        packed, jnp.asarray(s, jnp.bfloat16)).T
    err = np.abs(np.asarray(y, np.float32) - ref).max()
    assert err < 0.02 * max(1.0, np.abs(ref).max())


def test_supported_gates():
    bf16 = jnp.bfloat16
    # interpret=True bypasses the platform gate so the shape/dtype
    # logic is actually exercised on the CPU suite
    assert im.supported(8, 256, 256, bf16, interpret=True)
    assert not im.supported(8, 256, 250, bf16, interpret=True)   # K align
    assert not im.supported(2048, 256, 256, bf16, interpret=True)
    assert not im.supported(8, 256, 256, jnp.float32, interpret=True)
    # TPU gate: this suite runs on CPU, so even good shapes are gated
    assert not im.supported(8, 256, 256, bf16)
