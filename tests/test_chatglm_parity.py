"""ChatGLM2/3 numerical parity.

``transformers`` ships no chatglm class (public checkpoints rely on
``trust_remote_code``), so the torch side here is an independent
reimplementation of the public modeling_chatglm.py architecture
(THUDM/chatglm2-6b): fused query_key_value with bias in the block layout,
MQA with grouped kv heads, rotary over HALF the head dims in the
interleaved-pairs convention, RMSNorm, SwiGLU over a fused dense_h_to_4h,
untied output_layer.  The checkpoint round-trips through
``convert_checkpoint`` exactly like a downloaded one.

tests/fixtures/chatglm2_golden.npz holds the tiny model's WEIGHTS along
with the torch-produced logits/nll, so the golden test is self-contained:
it neither imports torch nor depends on torch's init RNG stream staying
stable across versions.
"""
import dataclasses
import json
import os.path as osp

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opencompass_tpu.nn import forward, greedy_generate, sequence_nll
from opencompass_tpu.nn.hf_convert import convert_checkpoint

B, S = 2, 12
V, D, H, K, HD, F, L = 128, 64, 4, 2, 16, 96, 2
GOLDEN = osp.join(osp.dirname(__file__), 'fixtures',
                  'chatglm2_golden.npz')

HF_CONFIG = {
    'model_type': 'chatglm', 'hidden_size': D, 'num_layers': L,
    'num_attention_heads': H, 'kv_channels': HD,
    'multi_query_attention': True, 'multi_query_group_num': K,
    'ffn_hidden_size': F, 'padded_vocab_size': V, 'seq_length': 128,
    'add_qkv_bias': True, 'rmsnorm': True, 'layernorm_epsilon': 1e-5,
    'tie_word_embeddings': False,
}


def _write_checkpoint(state_dict, tmp_path):
    """state_dict: checkpoint-name -> numpy array (fp32)."""
    from safetensors.numpy import save_file
    save_file({k: np.ascontiguousarray(v, dtype=np.float32)
               for k, v in state_dict.items()},
              str(tmp_path / 'model.safetensors'))
    (tmp_path / 'config.json').write_text(json.dumps(HF_CONFIG))
    return str(tmp_path)


def _jax_logits(path, toks):
    cfg, params = convert_checkpoint(path)
    cfg = dataclasses.replace(cfg, dtype='float32')
    assert cfg.rope_interleaved and cfg.rotary_pct == 0.5
    assert cfg.num_kv_heads == K and cfg.qkv_bias
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return cfg, params, np.asarray(
        forward(params, cfg, jnp.asarray(toks), use_flash=False))


def test_chatglm2_matches_committed_golden(tmp_path):
    """Torch-free: weights + expected logits both come from the fixture."""
    golden = np.load(GOLDEN)
    sd = {name[len('w::'):]: golden[name] for name in golden.files
          if name.startswith('w::')}
    assert sd, 'fixture is missing the committed weights'
    path = _write_checkpoint(sd, tmp_path)
    toks = golden['tokens']
    _, _, ours = _jax_logits(path, toks)
    scale = np.abs(golden['logits']).max()
    np.testing.assert_allclose(ours, golden['logits'],
                               rtol=0.0, atol=5e-3 * scale)
    nll = np.asarray(sequence_nll(
        jnp.asarray(ours), jnp.asarray(toks),
        jnp.ones(toks.shape, bool)))
    np.testing.assert_allclose(nll, golden['nll'], rtol=1e-3, atol=1e-3)


# -- live torch cross-check (independent reimplementation) -----------------

def _torch_model_and_toks():
    torch = pytest.importorskip('torch')

    def _rms(x, w, eps=1e-5):
        var = x.float().pow(2).mean(-1, keepdim=True)
        return (x.float() * torch.rsqrt(var + eps) * w.float()).to(x.dtype)

    def _rotary_cache(seq_len, rot_dim, base=10000.0):
        # modeling_chatglm.RotaryEmbedding.forward_impl
        theta = 1.0 / (base ** (torch.arange(0, rot_dim, 2).float()
                                / rot_dim))
        idx = torch.outer(torch.arange(seq_len).float(), theta)
        return torch.stack([torch.cos(idx), torch.sin(idx)], dim=-1)

    def _apply_rotary(x, cache):
        # x: (B,S,nh,hd); cache: (S, rot/2, 2) — interleaved pairs
        rot = cache.shape[-2] * 2
        xr, x_pass = x[..., :rot], x[..., rot:]
        xs = xr.reshape(*xr.shape[:-1], rot // 2, 2)
        cos = cache[..., 0].view(1, x.shape[1], 1, rot // 2)
        sin = cache[..., 1].view(1, x.shape[1], 1, rot // 2)
        out = torch.stack(
            [xs[..., 0] * cos - xs[..., 1] * sin,
             xs[..., 1] * cos + xs[..., 0] * sin], dim=-1)
        return torch.cat([out.flatten(-2), x_pass], dim=-1)

    class TinyChatGLM2(torch.nn.Module):

        def __init__(self):
            super().__init__()
            nn = torch.nn
            self.embed = nn.Embedding(V, D)
            self.layers = nn.ModuleList()
            for _ in range(L):
                blk = nn.Module()
                blk.ln1 = nn.Parameter(torch.ones(D))
                blk.qkv = nn.Linear(D, (H + 2 * K) * HD, bias=True)
                blk.dense = nn.Linear(H * HD, D, bias=False)
                blk.ln2 = nn.Parameter(torch.ones(D))
                blk.h4 = nn.Linear(D, 2 * F, bias=False)
                blk.h4o = nn.Linear(F, D, bias=False)
                self.layers.append(blk)
            self.lnf = nn.Parameter(torch.ones(D))
            self.out = nn.Linear(D, V, bias=False)

        def forward(self, tokens):
            Bq, Sq = tokens.shape
            x = self.embed(tokens)
            cache = _rotary_cache(Sq, HD // 2)
            causal = torch.tril(torch.ones(Sq, Sq, dtype=torch.bool))
            for blk in self.layers:
                h = _rms(x, blk.ln1)
                qkv = blk.qkv(h)
                q = qkv[..., :H * HD].view(Bq, Sq, H, HD)
                k = qkv[..., H * HD:(H + K) * HD].view(Bq, Sq, K, HD)
                v = qkv[..., (H + K) * HD:].view(Bq, Sq, K, HD)
                q = _apply_rotary(q, cache)
                k = _apply_rotary(k, cache)
                # kv group g serves q heads [g*ratio, (g+1)*ratio)
                k = k.repeat_interleave(H // K, dim=2)
                v = v.repeat_interleave(H // K, dim=2)
                scores = torch.einsum('bqhd,bkhd->bhqk', q.float(),
                                      k.float()) / (HD ** 0.5)
                scores = scores.masked_fill(~causal, float('-inf'))
                probs = torch.softmax(scores, dim=-1)
                attn = torch.einsum('bhqk,bkhd->bqhd', probs, v.float())
                x = x + blk.dense(
                    attn.reshape(Bq, Sq, H * HD).to(x.dtype))
                h2 = _rms(x, blk.ln2)
                gate, up = blk.h4(h2).chunk(2, dim=-1)
                x = x + blk.h4o(torch.nn.functional.silu(gate) * up)
            return self.out(_rms(x, self.lnf))

    torch.manual_seed(0)
    model = TinyChatGLM2().eval()
    toks = np.random.RandomState(0).randint(0, V, (B, S))
    return torch, model, toks


def torch_state_dict(model):
    """Checkpoint-name -> numpy, matching _CHATGLM_MAP."""
    pre = 'transformer.encoder.layers'
    sd = {'transformer.embedding.word_embeddings.weight':
          model.embed.weight,
          'transformer.encoder.final_layernorm.weight': model.lnf,
          'transformer.output_layer.weight': model.out.weight}
    for i, blk in enumerate(model.layers):
        sd[f'{pre}.{i}.input_layernorm.weight'] = blk.ln1
        sd[f'{pre}.{i}.self_attention.query_key_value.weight'] = \
            blk.qkv.weight
        sd[f'{pre}.{i}.self_attention.query_key_value.bias'] = blk.qkv.bias
        sd[f'{pre}.{i}.self_attention.dense.weight'] = blk.dense.weight
        sd[f'{pre}.{i}.post_attention_layernorm.weight'] = blk.ln2
        sd[f'{pre}.{i}.mlp.dense_h_to_4h.weight'] = blk.h4.weight
        sd[f'{pre}.{i}.mlp.dense_4h_to_h.weight'] = blk.h4o.weight
    return {k: v.detach().numpy() for k, v in sd.items()}


@pytest.mark.slow
def test_chatglm2_torch_parity(tmp_path):
    torch, model, toks = _torch_model_and_toks()
    path = _write_checkpoint(torch_state_dict(model), tmp_path)
    with torch.no_grad():
        ref = model(torch.tensor(toks)).float().numpy()
    cfg, params, ours = _jax_logits(path, toks)
    scale = np.abs(ref).max()
    np.testing.assert_allclose(ours, ref, rtol=0.0, atol=5e-3 * scale)
    # greedy continuation parity via repeated torch forward
    cur = torch.tensor(toks)
    for _ in range(5):
        with torch.no_grad():
            nxt = model(cur)[:, -1].argmax(-1, keepdim=True)
        cur = torch.cat([cur, nxt], dim=1)
    ours_gen, _ = greedy_generate(params, cfg, jnp.asarray(toks),
                                  jnp.ones((B, S), bool), 5)
    np.testing.assert_array_equal(np.asarray(ours_gen),
                                  cur[:, S:].numpy())
