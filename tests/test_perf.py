"""Perf subsystem: counters, TaskProfiler records, summarizer surfacing."""
import json
import os

import pytest

from opencompass_tpu.models import FakeModel
from opencompass_tpu.utils.perf import PerfCounters, TaskProfiler, device_call


def test_counters_and_device_call():
    c = PerfCounters()
    with device_call(c, tokens_in=10, tokens_out=4, samples=2):
        pass
    assert c.tokens_in == 10 and c.tokens_out == 4 and c.samples == 2
    assert c.calls == 1 and c.device_seconds >= 0
    snap = c.snapshot()
    with device_call(c, tokens_in=5, samples=1):
        pass
    d = c.delta_since(snap)
    assert d['tokens_in'] == 5 and d['samples'] == 1 and d['calls'] == 1


def test_device_call_none_is_noop():
    with device_call(None, tokens_in=10):
        pass  # must not raise


def test_fake_model_records_counters():
    model = FakeModel()
    model.get_ppl(['a b c', 'd e'])
    model.generate(['hello world'], max_out_len=4)
    assert model.perf.samples == 3
    assert model.perf.tokens_in == 5
    assert model.perf.tokens_out >= 1


def test_task_profiler_writes_record(tmp_path):
    model = FakeModel()
    out = str(tmp_path / 'perf' / 'fake' / 'ds.json')
    with TaskProfiler(model, out_path=out) as prof:
        model.get_ppl(['x y z'] * 4)
    assert os.path.exists(out)
    with open(out) as f:
        rec = json.load(f)
    assert rec['samples'] == 4
    assert rec['samples_per_sec'] > 0
    assert rec['tokens_per_sec'] > 0
    assert prof.record == rec


def test_task_profiler_writes_record_on_error(tmp_path):
    """A failed task's perf JSON must still be written (with the error
    attached) so it shows in the summarizer's perf table."""
    model = FakeModel()
    out = str(tmp_path / 'perf' / 'fake' / 'ds.json')
    with pytest.raises(RuntimeError):
        with TaskProfiler(model, out_path=out) as prof:
            model.get_ppl(['x y'])
            raise RuntimeError('device wedged')
    assert os.path.exists(out)
    with open(out) as f:
        rec = json.load(f)
    assert rec['samples'] == 1
    assert rec['error'] == 'RuntimeError: device wedged'
    assert prof.record == rec


def test_device_call_first_flag_splits_compile_time():
    c = PerfCounters()
    with device_call(c, samples=1, first=True):
        pass
    with device_call(c, samples=1):
        pass
    assert c.calls == 2 and c.first_calls == 1
    assert 0 <= c.compile_seconds <= c.device_seconds
    d = c.delta_since({})  # snapshot-less delta tolerates new fields
    assert d['first_calls'] == 1


def test_task_profiler_record_has_compile_split(tmp_path):
    model = FakeModel()
    out = str(tmp_path / 'p.json')
    with TaskProfiler(model, out_path=out):
        model.get_ppl(['a b c'])
    with open(out) as f:
        rec = json.load(f)
    assert 'compile_seconds' in rec and 'first_calls' in rec


def test_task_profiler_jax_trace(tmp_path):
    # trace path: records a real jax.profiler trace on the CPU backend
    import jax
    import jax.numpy as jnp

    class _M:
        pass

    model = _M()
    trace_dir = str(tmp_path / 'trace')
    with TaskProfiler(model, trace_dir=trace_dir):
        jnp.sum(jnp.arange(16.0)).block_until_ready()
    del jax
    # a trace produces at least one file under the dir (format varies)
    found = [f for _, _, fs in os.walk(trace_dir) for f in fs]
    assert found, 'no trace artifacts written'
