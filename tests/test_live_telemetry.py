"""Live telemetry plane: task heartbeats (atomic writes, torn-file
tolerance), run status aggregation, Prometheus text exposition, the
driver HTTP endpoint, the `cli status` command, and the heartbeat-aware
stall watchdog."""
import json
import os
import os.path as osp
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
FIXTURE_RUN = osp.join(REPO, 'tests', 'fixtures', 'obs_run')


@pytest.fixture(autouse=True)
def _isolated_tracer():
    from opencompass_tpu import obs
    obs.reset_obs()
    yield
    obs.reset_obs()


def _cpu_env():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    return env


# -- heartbeat writer -------------------------------------------------------

def test_heartbeat_schema_and_atomic_write(tmp_path):
    from opencompass_tpu.obs.live import Heartbeat
    obs_dir = str(tmp_path / 'obs')
    hb = Heartbeat(obs_dir, 'OpenICLInfer[tiny/demo-gen]', interval=0.0)
    hb.set_unit(0, 2, 'tiny/demo-gen')
    hb.progress(5, 100, batch_seconds=0.125)
    with open(hb.path) as f:
        rec = json.load(f)
    assert rec['v'] == 1
    assert rec['task'] == 'OpenICLInfer[tiny/demo-gen]'
    assert rec['pid'] == os.getpid()
    assert rec['state'] == 'running'
    assert rec['unit'] == 'tiny/demo-gen'
    assert (rec['units_done'], rec['units_total']) == (0, 2)
    assert (rec['done'], rec['total']) == (5, 100)
    assert rec['last_batch_seconds'] == 0.125
    assert isinstance(rec['ts'], float) and rec['ts'] > 0
    # atomic write protocol leaves no temp droppings behind
    leftovers = [f for f in os.listdir(osp.dirname(hb.path))
                 if f.endswith('.tmp')]
    assert leftovers == []
    hb.mark('done')
    with open(hb.path) as f:
        rec = json.load(f)
    assert rec['state'] == 'done'
    assert rec['units_done'] == rec['units_total'] == 2


def test_heartbeat_rate_limited_and_add(tmp_path):
    from opencompass_tpu.obs.live import Heartbeat
    hb = Heartbeat(str(tmp_path), 't', interval=3600.0)
    hb.progress(1, 10, force=True)        # forced: lands on disk
    hb.progress(2, 10)                    # rate-limited: skipped
    hb.add(3)                             # rate-limited too
    with open(hb.path) as f:
        assert json.load(f)['done'] == 1
    hb.mark('done')                       # terminal: always written
    with open(hb.path) as f:
        rec = json.load(f)
    assert rec['state'] == 'done' and rec['done'] == 5  # add kept state


def test_heartbeat_never_fails_on_unwritable_dir(tmp_path):
    """The never-fail contract: a broken telemetry sink cannot raise
    into the task."""
    from opencompass_tpu.obs.live import Heartbeat
    blocker = tmp_path / 'blocker'
    blocker.write_text('a file where obs/ should be')
    hb = Heartbeat(str(blocker / 'obs'), 't', interval=0.0)
    hb.set_unit(0, 1, 'x')
    hb.progress(1, 2, force=True)
    hb.add(1)
    hb.mark('done')                       # none of these may raise


def test_heartbeat_path_deterministic_and_collision_free(tmp_path):
    from opencompass_tpu.obs.live import heartbeat_path
    a = heartbeat_path('/obs', 'OpenICLInfer[model/ds one]')
    b = heartbeat_path('/obs', 'OpenICLInfer[model/ds_one]')
    assert a == heartbeat_path('/obs', 'OpenICLInfer[model/ds one]')
    assert a != b                         # sanitize-identical names differ
    base = osp.basename(a)
    assert base.endswith('.json')
    assert '/' not in base and '[' not in base and ' ' not in base


def test_init_task_heartbeat_follows_tracer(tmp_path):
    from opencompass_tpu import obs
    assert not obs.init_task_heartbeat('t').enabled   # untraced: noop
    obs.init_obs(str(tmp_path))
    hb = obs.init_task_heartbeat('t')
    assert hb.enabled and obs.get_heartbeat() is hb
    obs.reset_obs()
    assert not obs.get_heartbeat().enabled            # reset restores noop


def test_heartbeat_keepalive_refreshes_during_silent_compute(tmp_path):
    """A task blocked in one long device call makes no progress ticks;
    the keepalive thread must still refresh the file (the stall
    watchdog's liveness signal), and stand down once the task ends."""
    from opencompass_tpu.obs.live import Heartbeat
    hb = Heartbeat(str(tmp_path), 't', interval=0.1, keepalive=True)
    hb.progress(1, 10, force=True)
    mtime0 = os.stat(hb.path).st_mtime
    deadline = time.time() + 5
    while time.time() < deadline:          # no progress calls here
        if os.stat(hb.path).st_mtime > mtime0:
            break
        time.sleep(0.05)
    assert os.stat(hb.path).st_mtime > mtime0, 'keepalive never fired'
    hb.mark('done')
    time.sleep(0.3)                        # give a stray beat a chance
    mtime1 = os.stat(hb.path).st_mtime
    time.sleep(0.3)
    assert os.stat(hb.path).st_mtime == mtime1, \
        'keepalive kept beating after mark()'
    with open(hb.path) as f:
        assert json.load(f)['state'] == 'done'


# -- readers / aggregation --------------------------------------------------

def _write_heartbeat(obs_dir, name, **fields):
    from opencompass_tpu.obs.live import atomic_write_json, heartbeat_path
    rec = {'v': 1, 'task': name, 'pid': 1, 'ts': time.time(),
           'state': 'running', 'unit': None, 'units_done': 0,
           'units_total': None, 'done': 0, 'total': None}
    rec.update(fields)
    atomic_write_json(heartbeat_path(obs_dir, name), rec)
    return rec


def test_read_heartbeats_tolerates_torn_files(tmp_path):
    """Regression: a half-written progress file never crashes the
    aggregator — it is skipped and the valid files still load."""
    from opencompass_tpu.obs.live import build_status, read_heartbeats
    obs_dir = str(tmp_path)
    _write_heartbeat(obs_dir, 'good-task', done=3, total=9)
    progress = tmp_path / 'progress'
    (progress / 'torn.json').write_text('{"task": "x", "do')  # mid-write
    (progress / 'notdict.json').write_text('[1, 2, 3]')
    (progress / 'empty.json').write_text('')
    (progress / 'ignored.txt').write_text('not json at all')
    hbs = read_heartbeats(obs_dir)
    assert list(hbs) == ['good-task']
    assert hbs['good-task']['done'] == 3
    assert hbs['good-task']['heartbeat_age_seconds'] >= 0
    snap = build_status(obs_dir)          # and the full fold survives too
    assert snap['overall']['n_tasks'] == 1


def test_build_status_fractions_eta_and_state_merge(tmp_path):
    from opencompass_tpu.obs.live import build_status
    obs_dir = str(tmp_path)
    # mid-unit progress: 1 finished pair + 50/100 of the second = 75%
    _write_heartbeat(obs_dir, 'infer-a', units_done=1, units_total=2,
                     done=50, total=100, tokens_per_sec=99.5)
    # heartbeat says running, runner verdict says failed: runner wins
    _write_heartbeat(obs_dir, 'infer-b', done=10, total=10)
    now = time.time()
    snap = build_status(obs_dir, runner_state={
        'runner': 'OpenICLInferTask', 'started': now - 30.0,
        'state': 'running',
        'tasks': {'infer-a': {'state': 'running'},
                  'infer-b': {'state': 'failed', 'returncode': 1},
                  'infer-c': {'state': 'pending'}},
        'slots': {'in_use': 2, 'total': 4}}, now=now)
    tasks = snap['tasks']
    assert tasks['infer-a']['progress'] == pytest.approx(0.75)
    assert tasks['infer-a']['tokens_per_sec'] == 99.5
    assert tasks['infer-b']['state'] == 'failed'
    assert tasks['infer-b']['returncode'] == 1
    assert tasks['infer-c']['state'] == 'pending'
    o = snap['overall']
    assert o['n_tasks'] == 3
    # (0.75 + 1.0 [failed but fully progressed] + 0.0) / 3
    assert o['progress'] == pytest.approx((0.75 + 1.0 + 0.0) / 3,
                                          abs=1e-4)
    assert o['running'] == 1 and o['failed'] == 1 and o['pending'] == 1
    # eta = elapsed * (1-p)/p
    p = o['progress']
    assert o['eta_seconds'] == pytest.approx(30.0 * (1 - p) / p, abs=0.5)
    assert snap['slots'] == {'in_use': 2, 'total': 4}


def test_status_aggregator_persists_and_finalizes(tmp_path):
    from opencompass_tpu.obs.live import StatusAggregator, load_status
    obs_dir = str(tmp_path)
    (tmp_path / 'progress').mkdir()
    (tmp_path / 'progress' / 'torn.json').write_text('{"task"')  # hostile
    agg = StatusAggregator(obs_dir, runner='OpenICLInferTask',
                           interval=0.05, slots_probe=lambda: (1, 2))
    agg.set_tasks(['a', 'b'])
    agg.start()
    agg.task_started('a')
    deadline = time.time() + 5
    snap = None
    while time.time() < deadline:
        snap = load_status(obs_dir)
        if snap and snap['tasks'].get('a', {}).get('state') == 'running':
            break
        time.sleep(0.02)
    assert snap and snap['state'] == 'running'
    assert snap['tasks']['a']['state'] == 'running'
    assert snap['tasks']['b']['state'] == 'pending'
    assert snap['slots'] == {'in_use': 1, 'total': 2}
    agg.task_finished('a', 0)
    agg.task_finished('b', 0)
    agg.stop()
    snap = load_status(obs_dir)
    assert snap['state'] == 'done'
    assert snap['overall']['progress'] == 1.0
    assert snap['overall']['ok'] == 2
    assert snap['overall']['eta_seconds'] is None


def test_run_marker_overlay_between_phases(tmp_path):
    """A phase aggregator finishing is not the run finishing: while the
    driver's run.json says running (live pid), a 'done' phase snapshot
    reads back as 'running'; once the driver exits, 'done' wins."""
    from opencompass_tpu.obs.live import (StatusAggregator, current_status,
                                          mark_run)
    obs_dir = str(tmp_path)
    mark_run(obs_dir, 'running')           # our own (alive) pid
    agg = StatusAggregator(obs_dir, runner='OpenICLInferTask', interval=60)
    agg.set_tasks(['a'])
    agg.task_finished('a', 0)
    agg.stop()                             # phase snapshot: state done
    assert current_status(obs_dir)['state'] == 'running'
    mark_run(obs_dir, 'done')
    assert current_status(obs_dir)['state'] == 'done'


def test_run_marker_dead_pid_is_ignored(tmp_path):
    """A crashed driver's stale 'running' marker must not pin the
    status at running forever."""
    from opencompass_tpu.obs.live import (atomic_write_json,
                                          current_status, mark_run)
    obs_dir = str(tmp_path)
    import subprocess
    proc = subprocess.Popen(['sleep', '0.05'])
    proc.wait()                            # a pid known to be dead
    atomic_write_json(osp.join(obs_dir, 'run.json'),
                      {'v': 1, 'state': 'running', 'pid': proc.pid,
                       'ts': time.time(), 'started': time.time()})
    _write_heartbeat(obs_dir, 'a', state='done', units_done=1,
                     units_total=1)
    snap = current_status(obs_dir)
    assert snap['state'] == 'done'         # marker overruled


def test_aggregator_anchors_eta_at_run_start(tmp_path):
    """A later phase's ETA extrapolates over the whole run (run.json
    started), not the few seconds since its own phase began."""
    from opencompass_tpu.obs.live import (StatusAggregator,
                                          atomic_write_json, load_status)
    obs_dir = str(tmp_path)
    atomic_write_json(osp.join(obs_dir, 'run.json'),
                      {'v': 1, 'state': 'running', 'pid': os.getpid(),
                       'ts': time.time(), 'started': time.time() - 100.0})
    agg = StatusAggregator(obs_dir, runner='OpenICLEvalTask', interval=60)
    agg.set_tasks(['e1', 'e2'])
    agg.task_finished('e1', 0)
    agg.write_snapshot()
    snap = load_status(obs_dir)
    assert snap['elapsed_seconds'] == pytest.approx(100.0, abs=2.0)
    # p=0.5 over ~100s elapsed -> ~100s remaining, not ~0
    assert snap['overall']['eta_seconds'] == pytest.approx(100.0, rel=0.1)


# -- prometheus exposition --------------------------------------------------

def test_prometheus_counters_and_gauges():
    from opencompass_tpu.obs.metrics import MetricsRegistry
    from opencompass_tpu.obs.promexport import render_prometheus
    reg = MetricsRegistry()
    reg.counter('runner.task_retries').inc(3)
    reg.gauge('device.peak_bytes_in_use').set(7)
    reg.gauge('device.peak_bytes_in_use').set(4)
    text = render_prometheus(reg.snapshot())
    assert '# TYPE oct_runner_task_retries_total counter' in text
    assert 'oct_runner_task_retries_total 3' in text
    assert 'oct_device_peak_bytes_in_use 4' in text
    assert 'oct_device_peak_bytes_in_use_max 7' in text
    assert text.endswith('\n')


def test_prometheus_histogram_cumulative_invariant():
    """Registry counts are per-bucket; the exposition must be
    cumulative, monotone, and end at le=\"+Inf\" == count."""
    import re
    from opencompass_tpu.obs.metrics import MetricsRegistry
    from opencompass_tpu.obs.promexport import render_prometheus
    reg = MetricsRegistry()
    h = reg.histogram('inferencer.batch_seconds', buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.09, 0.5, 2.0, 99.0):
        h.observe(v)
    text = render_prometheus(reg.snapshot())
    buckets = re.findall(
        r'oct_inferencer_batch_seconds_bucket\{le="([^"]+)"\} (\d+)', text)
    assert [b[0] for b in buckets] == ['0.1', '1', '10', '+Inf']
    counts = [int(b[1]) for b in buckets]
    assert counts == [2, 3, 4, 5]                 # cumulative, monotone
    assert counts == sorted(counts)
    assert 'oct_inferencer_batch_seconds_count 5' in text
    assert 'oct_inferencer_batch_seconds_sum' in text


def test_prometheus_label_escaping_and_name_sanitizing():
    from opencompass_tpu.obs.promexport import (render_prometheus,
                                                sanitize_metric_name)
    assert sanitize_metric_name('a.b-c/d') == 'a_b_c_d'
    assert sanitize_metric_name('0weird')[0] == '_'
    hostile = 'task "quoted" back\\slash\nnewline'
    status = {'tasks': {hostile: {'progress': 0.5}},
              'overall': {}, 'slots': {}}
    text = render_prometheus({}, status=status)
    line = [ln for ln in text.splitlines()
            if ln.startswith('oct_task_progress{')][0]
    assert '\\"quoted\\"' in line
    assert 'back\\\\slash' in line
    assert '\\nnewline' in line
    assert '\n' not in line                       # stayed one sample line


def test_http_server_endpoints(tmp_path):
    from opencompass_tpu.obs.live import StatusAggregator
    from opencompass_tpu.obs.metrics import MetricsRegistry
    from opencompass_tpu.obs.promexport import ObsHTTPServer
    obs_dir = str(tmp_path)
    _write_heartbeat(obs_dir, 'live-task', done=4, total=8)
    agg = StatusAggregator(obs_dir, runner='R', interval=60)
    agg.write_snapshot()
    reg = MetricsRegistry()
    reg.counter('runner.task_retries').inc()
    server = ObsHTTPServer(obs_dir, port=0, registry=reg)
    port = server.start()
    assert port and port > 0
    with open(osp.join(obs_dir, 'http.json')) as f:
        assert json.load(f)['port'] == port
    base = f'http://127.0.0.1:{port}'
    assert urllib.request.urlopen(
        base + '/healthz', timeout=10).read() == b'ok\n'
    status = json.loads(urllib.request.urlopen(
        base + '/status', timeout=10).read().decode())
    assert status['v'] == 1
    assert status['tasks']['live-task']['done'] == 4
    resp = urllib.request.urlopen(base + '/metrics', timeout=10)
    assert 'text/plain' in resp.headers['Content-Type']
    metrics = resp.read().decode()
    assert 'oct_runner_task_retries_total 1' in metrics
    assert 'oct_task_examples_done{task="live-task"} 4' in metrics
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(base + '/nope', timeout=10)
    assert exc.value.code == 404
    server.stop()
    assert not osp.exists(osp.join(obs_dir, 'http.json'))


# -- `cli status` -----------------------------------------------------------

def test_status_cli_on_fixture_tree():
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'status',
         'tests/fixtures/obs_run'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert 'state: done' in r.stdout
    assert 'OpenICLInfer[tiny/demo-gen]' in r.stdout
    assert 'OpenICLInfer[tiny/demo-ppl]' in r.stdout
    assert '1 ok' in r.stdout and '1 failed' in r.stdout
    assert '96/128' in r.stdout and '75%' in r.stdout
    assert '100%' in r.stdout


def test_status_cli_json():
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'status',
         'tests/fixtures/obs_run', '--json'],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    snap = json.loads(r.stdout)
    assert snap['v'] == 1
    assert snap['overall'] == {'n_tasks': 2, 'progress': 0.875,
                               'eta_seconds': None, 'ok': 1, 'failed': 1,
                               'running': 0, 'pending': 0,
                               'hbm_used_frac': 0.88,
                               'hbm_high_water_frac': 0.94}


def test_status_cli_missing_tree(tmp_path):
    r = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'status',
         str(tmp_path)],
        cwd=REPO, env=_cpu_env(), capture_output=True, text=True,
        timeout=180)
    assert r.returncode == 1
    assert 'obs' in r.stdout


def test_status_falls_back_to_heartbeats_without_status_json(tmp_path):
    """A run whose aggregator died still renders from progress files."""
    from opencompass_tpu.obs.live import (current_status, render_status,
                                          resolve_obs_dir)
    obs_dir = str(tmp_path / 'run' / 'obs')
    _write_heartbeat(obs_dir, 'orphan-task', done=2, total=4,
                     units_total=1)
    assert resolve_obs_dir(str(tmp_path / 'run')) == obs_dir
    assert resolve_obs_dir(str(tmp_path)) == obs_dir   # parent scan
    snap = current_status(obs_dir)
    assert snap['tasks']['orphan-task']['progress'] == pytest.approx(0.5)
    text = render_status(snap)
    assert 'orphan-task' in text and '2/4' in text


# -- stall watchdog: heartbeat freshness beats log silence ------------------

def _stall_runner(stall_timeout):
    from opencompass_tpu.runners.local import LocalRunner
    runner = LocalRunner(task=dict(type='OpenICLInferTask'),
                         stall_timeout=stall_timeout)
    runner._watchdog_poll_s = 0.2
    return runner


def test_stall_watchdog_kills_silent_task_without_heartbeat(tmp_path):
    from opencompass_tpu import obs
    obs.init_obs(str(tmp_path))
    runner = _stall_runner(stall_timeout=0.8)
    rc = runner._run_once('sleep 30', dict(_cpu_env()),
                          str(tmp_path / 'task.out'), 'silent-task')
    assert rc == -9


def test_stall_watchdog_spares_heartbeating_task(tmp_path):
    """Regression for the false-kill: a task that computes silently
    (no log growth) past stall_timeout survives as long as its
    heartbeat file stays fresh."""
    from opencompass_tpu import obs
    from opencompass_tpu.obs.live import atomic_write_json, heartbeat_path
    tracer = obs.init_obs(str(tmp_path))
    hb_path = heartbeat_path(tracer.obs_dir, 'beating-task')
    stop = threading.Event()

    def beat():
        while not stop.wait(0.25):
            atomic_write_json(hb_path, {'task': 'beating-task',
                                        'ts': time.time()})

    thread = threading.Thread(target=beat, daemon=True)
    thread.start()
    try:
        runner = _stall_runner(stall_timeout=0.8)
        t0 = time.time()
        rc = runner._run_once('sleep 2.5', dict(_cpu_env()),
                              str(tmp_path / 'task.out'), 'beating-task')
    finally:
        stop.set()
        thread.join(timeout=5)
    assert rc == 0, 'heartbeating task was falsely stall-killed'
    assert time.time() - t0 >= 2.0        # outlived several stall windows
