"""Outbound API scheduler (opencompass_tpu/outbound/): AIMD
concurrency + pacing units under injected clocks, the shared
resilience primitives, typed transport errors against the stub
provider, scheduler behaviors (scatter-back, retry budgets, breaker
lifecycle, hedging, deadlines, fail-fast drain), the TokenBucket
parity shim, and the GenInferencer partial-failure/resume path."""
import json
import os
import os.path as osp
import threading
import time

import pytest

from opencompass_tpu.models.completions_api import CompletionsAPI
from opencompass_tpu.models.openai_api import OpenAI
from opencompass_tpu.outbound import (AimdLimiter, OutboundScheduler,
                                      Pacer, PartialFailure,
                                      RateLimited, Rejected,
                                      ServerError, StallError,
                                      StubProvider, canned_text,
                                      read_outbound)
from opencompass_tpu.outbound import errors as oerr


@pytest.fixture
def stub():
    provider = StubProvider(latency_s=0.01).start()
    yield provider
    provider.stop()


def _model(stub_provider, **kwargs):
    ob = dict(breaker_cooldown_s=0.3, retry_budget_rate=5.0,
              retry_budget_burst=8.0, request_timeout_s=10.0)
    ob.update(kwargs.pop('outbound', {}))
    defaults = dict(path='m', key='k',
                    openai_api_base=stub_provider.chat_url,
                    query_per_second=1000, retry=2, outbound=ob)
    defaults.update(kwargs)
    return OpenAI(**defaults)


# -- shared primitives -------------------------------------------------------

def test_resilience_primitives_are_shared():
    """One RetryBudget/backoff/CircuitBreaker implementation serves
    both the serve daemon and the outbound plane (acceptance: a fix in
    one is a fix in both)."""
    from opencompass_tpu.serve import scheduler as serve_sched
    from opencompass_tpu.utils import resilience
    assert serve_sched.RetryBudget is resilience.RetryBudget
    assert serve_sched.CircuitBreaker is resilience.CircuitBreaker
    assert serve_sched.backoff_delay is resilience.backoff_delay
    assert serve_sched.CircuitOpenError is resilience.CircuitOpenError
    sched = OutboundScheduler('prov-shared')
    assert isinstance(sched.budget, resilience.RetryBudget)
    assert isinstance(sched.breaker, resilience.CircuitBreaker)


# -- limits (injected clocks) ------------------------------------------------

def test_aimd_limiter_throttle_and_recovery():
    lim = AimdLimiter(max_limit=8, min_limit=1, hold_s=1.0)
    assert lim.snapshot()['limit'] == 8
    lim.on_throttle(now=100.0)
    assert lim.snapshot()['limit'] == 4
    # within the hold window a second throttle is one incident, not a
    # collapse to the floor
    lim.on_throttle(now=100.5)
    assert lim.snapshot()['limit'] == 4
    lim.on_throttle(now=101.5)
    assert lim.snapshot()['limit'] == 2
    assert lim.snapshot()['low_water'] == 2
    # additive increase creeps back up on success
    for _ in range(50):
        lim.on_success()
    assert lim.snapshot()['limit'] > 2
    assert lim.snapshot()['low_water'] == 2   # the evidence survives


def test_aimd_limiter_bounds_inflight():
    lim = AimdLimiter(max_limit=2)
    assert lim.acquire(timeout=0.1)
    assert lim.acquire(timeout=0.1)
    t0 = time.perf_counter()
    assert not lim.acquire(timeout=0.15)     # window full
    assert time.perf_counter() - t0 >= 0.14
    lim.release()
    assert lim.acquire(timeout=0.1)
    lim.release()
    lim.release()


def test_pacer_qps_and_retry_after_hold():
    pacer = Pacer(qps=10)                     # 100ms interval
    assert pacer.reserve(now=50.0) == 0.0
    assert pacer.reserve(now=50.0) == pytest.approx(0.1)
    assert pacer.reserve(now=50.0) == pytest.approx(0.2)
    # a Retry-After hold gates EVERY launch, and only ever extends
    pacer.hold(5.0, now=50.0)
    pacer.hold(2.0, now=50.0)                 # shorter: ignored
    assert pacer.reserve(now=50.3) == pytest.approx(4.7)
    # no-qps pacer is free until held
    free = Pacer()
    assert free.reserve(now=1.0) == 0.0
    assert free.reserve(now=1.0) == 0.0


def test_token_bucket_shim_clock_disciplined():
    """The parity shim: no refill thread, no Semaphore._value, tokens
    accrue lazily on the injected clock."""
    from opencompass_tpu.models.base_api import TokenBucket
    threads_before = threading.active_count()
    bucket = TokenBucket(2.0)                 # 2 qps
    assert bucket.try_take(now=10.0) == 0.0   # initial token
    wait = bucket.try_take(now=10.0)
    assert wait == pytest.approx(0.5)         # 1/rate to the next
    assert bucket.try_take(now=10.5) == 0.0   # accrued
    # burst caps at rate: a long idle gap does not bank unlimited qps
    for _ in range(2):
        assert bucket.try_take(now=100.0) == 0.0
    assert bucket.try_take(now=100.0) > 0.0
    assert threading.active_count() == threads_before  # no daemon thread
    bucket.get_token()                        # the blocking call works


# -- typed transport ---------------------------------------------------------

def test_post_json_once_typed_errors(stub):
    model = _model(stub)
    url = stub.chat_url
    body = {'messages': [{'role': 'user', 'content': 'hi'}]}
    stub.queue_429(1, retry_after_s=0.7)
    with pytest.raises(RateLimited) as exc:
        model.post_json_once(url, body)
    assert exc.value.retry_after_s == pytest.approx(0.7)
    assert exc.value.status == 429
    stub.set_mode('401')
    with pytest.raises(Rejected):
        model.post_json_once(url, body)
    stub.set_mode('500')
    with pytest.raises(ServerError):
        model.post_json_once(url, body)
    stub.set_mode('malformed')
    with pytest.raises(oerr.MalformedResponse):
        model.post_json_once(url, body)
    stub.set_mode('stall')
    with pytest.raises(StallError):
        model.post_json_once(url, body, timeout=0.3)
    stub.set_mode(None)
    assert model.post_json_once(url, body)['choices']


def test_post_json_honors_retry_after(stub):
    """Satellite: the retrying post_json sleeps at least the 429's
    Retry-After before re-sending (previously a synchronized
    2**attempt stampede that ignored the header)."""
    model = _model(stub)
    stub.queue_429(1, retry_after_s=0.4)
    body = {'messages': [{'role': 'user', 'content': 'ra probe'}]}
    out = model.post_json(stub.chat_url, body)
    assert out['choices'][0]['message']['content'] \
        == canned_text('ra probe')
    log = stub.log()
    assert len(log) == 2                      # 429 then the retry
    assert log[1]['t'] - log[0]['t'] >= 0.38  # header honored


def test_backoff_jitter_decorrelates():
    from opencompass_tpu.utils.resilience import backoff_delay
    d_a = backoff_delay('provider-a#1', 0, base_s=1.0, cap_s=30.0)
    d_b = backoff_delay('provider-b#1', 0, base_s=1.0, cap_s=30.0)
    assert d_a != d_b                          # no lockstep stampede
    assert 0.5 <= d_a < 1.0 and 0.5 <= d_b < 1.0
    # deterministic: an incident replays with the same delays
    assert backoff_delay('provider-a#1', 0, base_s=1.0,
                         cap_s=30.0) == d_a


# -- scheduler behaviors -----------------------------------------------------

def test_scheduler_scatter_back(stub):
    model = _model(stub)
    rows = [f'scatter {i}' for i in range(12)]
    delivered = {}
    report = model.generate_outcomes(
        rows, 8, on_result=lambda i, v: delivered.__setitem__(i, v))
    assert report.values() == [canned_text(r) for r in rows]
    # every row delivered through the scatter-back hook, exactly once,
    # with the right index mapping
    assert delivered == {i: canned_text(r) for i, r in enumerate(rows)}


def test_scheduler_adapts_to_429_and_bounds_retries(stub):
    model = _model(stub, max_inflight=6)
    sched = model.outbound_scheduler()
    stub.queue_429(8, retry_after_s=0.1)
    out = model.generate([f'adapt {i}' for i in range(16)],
                         max_out_len=8)
    assert len(out) == 16
    stats = sched.stats()
    assert stats['http_429_total'] >= 1
    # the AIMD window backed off below its ceiling under the burst
    assert stats['limiter']['low_water'] < 6
    # every retry drew a budget token: retries never exceed failures
    assert stats['retries_total'] <= stats['http_429_total'] \
        + stats['http_5xx_total']
    # the pacer recorded the provider-directed holds
    assert stats['pacer']['holds'] >= 1


def test_retry_budget_refusal_stops_amplification(stub):
    """An exhausted budget surfaces the failure instead of piling
    retries onto a failing provider."""
    model = _model(stub, retry=3,
                   outbound=dict(retry_budget_rate=0.0,
                                 retry_budget_burst=1.0))
    stub.set_mode('500')
    report = model.generate_outcomes([f'b{i}' for i in range(4)], 8)
    stats = model.outbound_scheduler().stats()
    assert all(not o.ok for o in report.outcomes)
    assert stats['retries_total'] <= 1         # the single burst token
    assert stats['retry_budget_refusals'] >= 1
    kinds = {o.failure.kind for o in report.outcomes}
    assert kinds <= {'server_error', 'breaker_open', 'aborted'}


def test_breaker_lifecycle_open_probe_close(stub):
    model = _model(stub)
    sched = model.outbound_scheduler()
    stub.set_mode('500')
    with pytest.raises(PartialFailure):
        model.generate(['c1', 'c2', 'c3'], max_out_len=8)
    assert sched.breaker.state in ('open', 'half_open')
    opens_before = sched.breaker.opens
    stub.set_mode(None)
    time.sleep(0.4)                            # past the 0.3s cooldown
    # the next call is the half-open probe; success closes the circuit
    assert model.generate(['probe'], max_out_len=8) \
        == [canned_text('probe')]
    assert sched.breaker.state == 'closed'
    assert sched.breaker.opens == opens_before


def test_hedging_beats_straggler(stub):
    stub.set_stall_s(5.0)
    model = _model(stub, hedge_after_s=0.25,
                   outbound=dict(request_timeout_s=8.0))
    stub.queue_stall(1)                        # only the first stalls
    t0 = time.perf_counter()
    out = model.generate(['straggler row'], max_out_len=8)
    wall = time.perf_counter() - t0
    assert out == [canned_text('straggler row')]
    assert wall < 4.0                          # did not eat the stall
    stats = model.outbound_scheduler().stats()
    assert stats['hedges_total'] == 1
    assert stats['hedge_wins_total'] == 1


def test_deadline_bounds_stalled_provider(stub):
    model = _model(stub)
    stub.set_mode('stall')
    t0 = time.perf_counter()
    report = model.generate_outcomes(['dl row'], 8, deadline_s=0.8)
    wall = time.perf_counter() - t0
    outcome = report.outcomes[0]
    assert not outcome.ok
    assert outcome.failure.kind in ('deadline_exceeded', 'stall')
    assert wall < 6.0


def test_deadline_forwarded_on_outbound_request(stub):
    """The remaining row budget rides X-OCT-Deadline-Ms to the
    provider (deadline propagation through scheduler threads)."""
    model = _model(stub)
    report = model.generate_outcomes(['fw row'], 8, deadline_s=30.0)
    assert report.outcomes[0].ok
    fwd = [r['deadline_ms'] for r in stub.log()
           if r['prompt'].endswith('fw row')]
    assert fwd and fwd[0] is not None
    assert 0 < float(fwd[0]) <= 30000


def test_fail_fast_drains_dead_endpoint(stub):
    """Satellite: a dead endpoint (non-retryable auth failure) stops
    admitting queued siblings and leaks no request threads past the
    call."""
    stub.set_mode('401')
    model = _model(stub, max_inflight=4)
    threads_before = threading.active_count()
    with pytest.raises(PartialFailure) as exc:
        model.generate([f'dead {i}' for i in range(30)], max_out_len=8)
    kinds = {f.kind for f in exc.value.failures}
    assert kinds == {'rejected', 'aborted'}
    # fail-fast: far fewer requests than rows reached the endpoint
    assert stub.stats()['requests_total'] < 30
    # the scheduler joined its workers: no leaked threads
    time.sleep(0.2)
    assert threading.active_count() <= threads_before + 1


def test_all_failed_message_keeps_attempt_count(monkeypatch):
    """Contract pinned by PR reviewers past: a dead endpoint raises
    RuntimeError naming the attempt count (see also
    test_icl_extras.test_openai_raises_after_retry_budget)."""
    model = OpenAI(path='m', key='k', retry=0, query_per_second=100)
    import unittest.mock as mock
    with mock.patch('urllib.request.urlopen',
                    side_effect=OSError('no network')):
        with pytest.raises(RuntimeError,
                           match='failed after 1 attempts'):
            model.generate(['ping'], max_out_len=4)


def test_fail_fast_off_keeps_siblings_running():
    """fail_fast=False: one row's non-retryable rejection must not
    abort the queued siblings."""
    sched = OutboundScheduler('prov-ff', max_inflight=2)

    def call(prompt, timeout):
        if 'REJECTME' in prompt:
            raise Rejected('bad row')
        time.sleep(0.05)     # healthy rows slow enough that siblings
        return f'ok {prompt}'   # are still queued when rejection lands

    rows = ['a', 'b REJECTME', 'c', 'd', 'e']
    report = sched.run(rows, call, fail_fast=False)
    kinds = {o.failure.kind for o in report.outcomes if o.failure}
    assert kinds == {'rejected'}               # nothing aborted
    assert sum(1 for o in report.outcomes if o.ok) == 4
    # and with the default fail_fast=True the drain kicks in
    report2 = sched.run(['x REJECTME'] + [f'r{i}' for i in range(20)],
                        call)
    kinds2 = {o.failure.kind for o in report2.outcomes if o.failure}
    assert 'aborted' in kinds2
    assert 'rejected' in kinds2


def test_collector_error_surfaces_as_typed_failure():
    """An on_result that fails to persist a row must turn that row
    into a typed failure — never an ok outcome the caller finalizes
    with the row silently missing."""
    sched = OutboundScheduler('prov-coll', max_inflight=1)

    def call(prompt, timeout):
        return f'ok {prompt}'

    def exploding_collector(i, value):
        if i == 2:                              # the LAST row
            raise OSError('disk full')

    report = sched.run(['a', 'b', 'c'], call,
                       on_result=exploding_collector)
    failures = {f.index: f.kind for f in report.failures}
    assert failures == {2: 'collector_error'}
    with pytest.raises(PartialFailure):
        report.values()


def test_unserializable_body_is_rejected_not_provider_fault(stub):
    """A client-side serialization bug must not burn retries or the
    provider breaker (it is not the provider's fault)."""
    model = _model(stub, generation_kwargs={'bad': {1, 2, 3}})
    with pytest.raises(PartialFailure) as exc:
        model.generate(['ser row'], max_out_len=8)
    assert exc.value.failures[0].kind == 'rejected'
    assert 'not JSON-serializable' in exc.value.failures[0].error
    assert stub.stats()['requests_total'] == 0   # never hit the wire
    assert model.outbound_scheduler().breaker.state == 'closed'


def test_hedge_win_accounting_exact():
    """hedge_wins_total counts only races the hedge actually won —
    a hedge that launched but lost to the primary is not a win."""
    calls = {'n': 0}

    def call(prompt, timeout):
        calls['n'] += 1
        if calls['n'] == 1:
            time.sleep(0.5)                     # slow primary, wins
        else:
            time.sleep(1.5)                     # slower hedge
        return 'ok'

    sched = OutboundScheduler('prov-hw', max_inflight=4,
                              hedge_after_s=0.1)
    report = sched.run(['row'], call)
    assert report.outcomes[0].ok
    stats = sched.stats()
    assert stats['hedges_total'] == 1
    assert stats['hedge_wins_total'] == 0
    assert report.outcomes[0].hedged is False   # the primary's result


def test_abandoned_attempt_keeps_its_inflight_slot():
    """A hedge win abandons the primary to its timeout — but the
    primary keeps holding its AIMD slot until its request actually
    ends, so true concurrency never exceeds the window."""
    release = threading.Event()
    calls = {'n': 0}

    def call(prompt, timeout):
        calls['n'] += 1
        if calls['n'] == 1:
            release.wait(5.0)                   # primary wedged
            return 'late'
        return 'fast'                           # hedge wins

    sched = OutboundScheduler('prov-slot', max_inflight=2,
                              hedge_after_s=0.1)
    report = sched.run(['row'], call)
    assert report.outcomes[0].ok
    assert report.outcomes[0].hedged is True
    assert sched.stats()['hedge_wins_total'] == 1
    # the abandoned primary still owns one slot
    assert sched.limiter.snapshot()['inflight'] == 1
    release.set()
    deadline = time.monotonic() + 3.0
    while sched.limiter.snapshot()['inflight'] and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert sched.limiter.snapshot()['inflight'] == 0


def test_open_breaker_sheds_fast_with_long_cooldown():
    """A provider that is DOWN fails the whole sweep in seconds: once
    the (default, 15s-cooldown) breaker opens, queued rows shed typed
    immediately instead of serializing through the cooldown."""
    from opencompass_tpu.outbound.errors import NetworkError
    sched = OutboundScheduler('prov-down', max_inflight=4,
                              max_attempts=2)

    def call(prompt, timeout):
        raise NetworkError('connection refused')

    t0 = time.perf_counter()
    report = sched.run([f'r{i}' for i in range(24)], call)
    wall = time.perf_counter() - t0
    assert all(not o.ok for o in report.outcomes)
    kinds = {o.failure.kind for o in report.outcomes}
    assert kinds <= {'network', 'breaker_open'}
    assert 'breaker_open' in kinds             # the breaker DID open
    assert wall < 10.0                         # no cooldown serialization


def test_post_json_fails_fast_on_non_retryable(monkeypatch):
    """post_json must not back off and retry an error another attempt
    cannot fix (e.g. an already-expired request deadline)."""
    from opencompass_tpu.obs import reqtrace
    model = OpenAI(path='m', key='k', retry=3, query_per_second=1000)
    token, _ = reqtrace.begin_request('req-dead', 'POST', '/x',
                                      deadline_ms=0.001)
    try:
        time.sleep(0.01)                       # budget now expired
        sleeps = []
        monkeypatch.setattr('opencompass_tpu.models.base_api.sleep',
                            sleeps.append)
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match='budget exhausted'):
            model.post_json('http://127.0.0.1:9/never', {'a': 1})
        assert time.perf_counter() - t0 < 1.0
        assert sleeps == []                    # zero backoff sleeps
    finally:
        reqtrace.end_request(token)


def test_report_stats_are_per_run_deltas(stub):
    """A scheduler shared across tasks attributes each run only its
    own traffic (flight-recorder/heartbeat numbers must not
    double-count the previous task)."""
    model = _model(stub)
    stub.queue_429(2, retry_after_s=0.05)
    first = model.generate_outcomes([f'a{i}' for i in range(6)], 8)
    assert first.stats['http_429_total'] == 2
    second = model.generate_outcomes([f'b{i}' for i in range(4)], 8)
    assert second.stats['http_429_total'] == 0   # clean second run
    assert second.stats['rows_total'] == 4
    assert second.stats['ok_total'] == 4
    # the scheduler's own lifetime view still accumulates
    assert model.outbound_scheduler().stats()['http_429_total'] == 2


def test_transient_4xx_and_internal_classification():
    """408/425 are transient (retryable stall, never sweep-fatal);
    client-side programmer errors are non-retryable `internal` and
    never feed the provider breaker."""
    import urllib.error
    err408 = oerr.from_http_error(urllib.error.HTTPError(
        'http://x', 408, 'Request Timeout', None, None))
    assert isinstance(err408, StallError) and err408.retryable
    assert oerr.classify(NotImplementedError('hook missing')).kind \
        == 'internal'

    sched = OutboundScheduler('prov-int', max_inflight=2, max_attempts=3)

    def call(prompt, timeout):
        raise NotImplementedError('transport hook missing')

    report = sched.run(['a', 'b'], call)
    assert {o.failure.kind for o in report.outcomes} == {'internal'}
    assert all(o.attempts == 1 for o in report.outcomes)  # no retries
    assert sched.breaker.state == 'closed'    # not a provider incident
    assert sched.stats()['retries_total'] == 0


def test_breaker_shed_counter_counts_only_sheds():
    """Riding out a short cooldown is not a shed."""
    from opencompass_tpu.utils.resilience import CircuitBreaker
    breaker = CircuitBreaker('prov-rs', cooldown_s=0.3)
    for _ in range(3):
        breaker.note_failure('boom')
    assert breaker.state == 'open'
    sched = OutboundScheduler('prov-rs', max_inflight=2,
                              max_attempts=3, breaker=breaker)
    report = sched.run(['row'], lambda p, t: 'ok')
    assert report.outcomes[0].ok              # waited out the cooldown
    assert sched.stats()['breaker_sheds_total'] == 0


# -- completions API through the scheduler -----------------------------------

def test_completions_api_rides_scheduler(stub):
    model = CompletionsAPI(path='m', url=stub.completions_url, key='',
                           query_per_second=1000, retry=1)
    out = model.generate(['alpha', 'beta'], max_out_len=8)
    assert out == [canned_text('alpha'), canned_text('beta')]
    ppl = model.get_ppl(['one two three'])
    assert ppl == [1.0]                        # stub echoes -1.0 each
    stats = model.outbound_scheduler().stats()
    assert stats['ok_total'] >= 3              # gen + ppl shared one
    assert stats['provider'] == f'127.0.0.1:{stub.port}'


# -- observability -----------------------------------------------------------

def test_outbound_metrics_and_snapshot(stub, tmp_path):
    from opencompass_tpu import obs
    tracer = obs.init_obs(str(tmp_path), enabled=True)
    try:
        model = _model(stub)
        model.generate(['obs row'], max_out_len=8)
        snap = tracer.metrics.snapshot()
        fams = {k.split('#')[0] for k in snap.get('gauges', {})}
        assert {'oct_outbound_inflight', 'oct_outbound_limit',
                'oct_outbound_qps', 'oct_outbound_breaker_state',
                'oct_outbound_http_429_total'} <= fams
        # the durable snapshot landed in the run's obs dir
        loaded = read_outbound(tracer.obs_dir)
        assert loaded is not None
        provider = loaded['providers'][model.provider_key]
        assert provider['ok_total'] >= 1
    finally:
        obs.init_obs(str(tmp_path), enabled=False)


def test_doctor_api_throttled_rule(tmp_path):
    from opencompass_tpu.obs import doctor
    serve_obs = tmp_path / 'cache' / 'serve' / 'obs'
    serve_obs.mkdir(parents=True)
    (tmp_path / 'cache' / 'serve' / 'queue').mkdir()
    snapshot = {'v': 1, 'ts': 1.0, 'pid': 1, 'providers': {
        'api.example.com': {
            'attempts_total': 50, 'http_429_total': 20,
            'retries_total': 15, 'retry_budget_refusals': 2,
            'limiter': {'limit': 2.0, 'max_limit': 8,
                        'low_water': 1.0},
            'breaker': {'state': 'closed', 'opens': 0},
        }}}
    (serve_obs / 'outbound.json').write_text(json.dumps(snapshot))
    report = doctor.diagnose(str(tmp_path / 'cache'))
    rules = {f['rule']: f for f in report['findings']}
    assert 'api_throttled' in rules
    finding = rules['api_throttled']
    assert finding['severity'] == 'warn'
    assert 'api.example.com' in finding['title']
    # breaker-open variant escalates the wording
    snapshot['providers']['api.example.com']['http_429_total'] = 0
    snapshot['providers']['api.example.com']['breaker'] = {
        'state': 'open', 'opens': 3, 'last_error': 'boom'}
    (serve_obs / 'outbound.json').write_text(json.dumps(snapshot))
    report = doctor.diagnose(str(tmp_path / 'cache'))
    rules = {f['rule']: f for f in report['findings']}
    assert 'crash-looping' in rules['api_throttled']['title']


def test_top_renders_outbound_pane(tmp_path):
    from opencompass_tpu.serve import top
    serve_obs = tmp_path / 'serve' / 'obs'
    serve_obs.mkdir(parents=True)
    (serve_obs / 'outbound.json').write_text(json.dumps(
        {'v': 1, 'ts': 1.0, 'pid': 1, 'providers': {
            'api.example.com': {
                'http_429_total': 7, 'retries_total': 3,
                'hedges_total': 2, 'hedge_wins_total': 1,
                'failed_total': 1, 'measured_qps': 2.5,
                'limiter': {'limit': 4.0, 'max_limit': 8},
                'breaker': {'state': 'open', 'opens': 1},
            }}}))
    snap = top.gather(str(tmp_path), now=2.0)
    out = top.render(snap)
    assert 'outbound[api.example.com]' in out
    assert '429 7' in out and 'breaker OPEN' in out


# -- inferencer wiring -------------------------------------------------------

def _toy_dataset(n=8, fail_rows=()):
    from datasets import Dataset, DatasetDict

    from opencompass_tpu.datasets.base import BaseDataset

    class Toy(BaseDataset):
        @staticmethod
        def load():
            rows = [{'q': f'question {i}'
                     + (' FAILME' if i in fail_rows else ''),
                     'a': 'x'} for i in range(n)]
            return DatasetDict({'train': Dataset.from_list(rows),
                                'test': Dataset.from_list(rows)})

    return Toy(reader_cfg=dict(input_columns=['q'],
                               output_column='a'))


def test_gen_inferencer_partial_failure_resumes_bit_identical(
        stub, tmp_path):
    """The tentpole's scatter-back contract end to end: mid-sweep row
    failures become typed api_errors.json records, successes flush,
    the task raises resumable, and the rerun recomputes ONLY the
    failed rows, converging bit-identically with a clean run."""
    from opencompass_tpu.icl import PromptTemplate
    from opencompass_tpu.icl.inferencers import GenInferencer
    from opencompass_tpu.icl.retrievers import ZeroRetriever
    ds = _toy_dataset(8, fail_rows=(2, 5))
    out_dir = str(tmp_path / 'preds')
    model = _model(stub, retry=1)
    template = PromptTemplate('Q: {q}\nA:')
    stub.set_fail_marker('FAILME')
    inf = GenInferencer(model=model, max_out_len=8, batch_size=4,
                        output_json_filepath=out_dir, save_every=1)
    with pytest.raises(PartialFailure):
        inf.inference(ZeroRetriever(ds), prompt_template=template)
    # typed, durable error records for exactly the failed rows
    errs = json.load(open(osp.join(out_dir, 'api_errors.json')))
    assert sorted(r['index'] for r in errs['failed_rows']) == [2, 5]
    assert all(r['kind'] for r in errs['failed_rows'])
    # successes flushed with holes where the failures were
    tmp = json.load(open(osp.join(out_dir, 'tmp_predictions')))
    assert sorted(int(k) for k in tmp) == [0, 1, 3, 4, 6, 7]

    stub.set_fail_marker(None)
    time.sleep(0.4)                    # breaker cooldown from the 500s
    before = stub.stats()['requests_total']
    inf2 = GenInferencer(model=model, max_out_len=8, batch_size=4,
                         output_json_filepath=out_dir, save_every=1)
    preds = inf2.inference(ZeroRetriever(ds), prompt_template=template)
    # the resume computed exactly the two failed rows
    assert stub.stats()['requests_total'] - before == 2
    assert not osp.exists(osp.join(out_dir, 'api_errors.json'))

    clean_dir = str(tmp_path / 'clean')
    inf3 = GenInferencer(model=_model(stub, retry=1), max_out_len=8,
                         batch_size=4, output_json_filepath=clean_dir)
    clean = inf3.inference(ZeroRetriever(ds), prompt_template=template)
    assert preds == clean              # bit-identical convergence


def test_gen_inferencer_outbound_rows_tick_heartbeat(stub, tmp_path):
    """Per-row progress (not batch jumps) rides the heartbeat, like
    the continuous-engine path."""
    from opencompass_tpu import obs
    from opencompass_tpu.icl import PromptTemplate
    from opencompass_tpu.icl.inferencers import GenInferencer
    from opencompass_tpu.icl.retrievers import ZeroRetriever
    from opencompass_tpu.obs.live import (Heartbeat, install_heartbeat,
                                          reset_heartbeat)
    obs.init_obs(str(tmp_path), enabled=True)
    try:
        hb = install_heartbeat(
            Heartbeat(str(tmp_path / 'obs'), 'api-task', interval=0))
        ds = _toy_dataset(6)
        inf = GenInferencer(model=_model(stub), max_out_len=8,
                            batch_size=3,
                            output_json_filepath=str(tmp_path / 'p'))
        preds = inf.inference(
            ZeroRetriever(ds),
            prompt_template=PromptTemplate('Q: {q}\nA:'))
        assert len(preds) == 6
        beat = json.load(open(hb.path))
        assert beat['done'] == 6
        assert beat.get('outbound_limit') is not None
        hb.mark('done')
    finally:
        reset_heartbeat()
        obs.init_obs(str(tmp_path), enabled=False)
