"""Weight-only int8 quantization: numerics stay close to the full-precision
model, decode runs, and tensor-parallel sharding accepts the int8 pytree."""
import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opencompass_tpu.models import JaxLM
from opencompass_tpu.nn import (TransformerConfig, forward, greedy_generate,
                                init_params, sequence_nll)
from opencompass_tpu.nn.quant import quantize_params


CFG = TransformerConfig.tiny()


def _data(B=2, S=16):
    key = jax.random.PRNGKey(7)
    tokens = jax.random.randint(key, (B, S), 0, CFG.vocab_size)
    return tokens, jnp.ones((B, S), bool)


def test_quantized_forward_close_to_fp():
    params = init_params(CFG, jax.random.PRNGKey(0))
    qparams = quantize_params(params, CFG)
    tokens, mask = _data()
    ref = forward(params, CFG, tokens, mask, use_flash=False)
    got = forward(qparams, CFG, tokens, mask, use_flash=False)
    # per-channel int8 on a tiny random model: logits track closely
    ref_n, got_n = np.asarray(ref), np.asarray(got)
    denom = np.maximum(np.abs(ref_n).max(), 1e-6)
    assert np.abs(ref_n - got_n).max() / denom < 0.05
    # and the induced NLL difference is small
    nll_ref = np.asarray(sequence_nll(ref, tokens, mask))
    nll_got = np.asarray(sequence_nll(got, tokens, mask))
    np.testing.assert_allclose(nll_got, nll_ref, rtol=0.02)


def test_quantized_weights_are_int8():
    params = init_params(CFG, jax.random.PRNGKey(0))
    q = quantize_params(params, CFG)
    layers = q['layers']
    assert layers['q']['w'].dtype == jnp.int8
    assert layers['down']['w'].dtype == jnp.int8
    assert 's' in layers['q'] and layers['q']['s'].shape \
        == layers['q']['w'].shape[:-1]
    # embeddings / norms untouched
    assert q['embed'].dtype == params['embed'].dtype
    # quantized tensors shrink by the source itemsize (bf16: 2x, fp32: 4x)
    orig = params['layers']['q']['w']
    assert layers['q']['w'].nbytes * orig.dtype.itemsize == orig.nbytes


def test_quantized_decode_runs():
    params = quantize_params(init_params(CFG, jax.random.PRNGKey(0)), CFG)
    tokens, mask = _data()
    out, lengths = jax.jit(
        lambda p, t, m: greedy_generate(p, CFG, t, m, 8))(params, tokens,
                                                          mask)
    assert out.shape == (2, 8)


def test_jaxlm_quantize_end_to_end():
    lm = JaxLM(config='tiny', max_seq_len=128, quantize='int8')
    lm_fp = JaxLM(config='tiny', max_seq_len=128)
    nll_q = lm.get_ppl(['hello world this is a test'])
    nll_fp = lm_fp.get_ppl(['hello world this is a test'])
    np.testing.assert_allclose(nll_q, nll_fp, rtol=0.05)
    assert lm.generate(['abc'], max_out_len=4)[0] is not None


def test_quantized_tensor_parallel_matches_single():
    if len(jax.devices()) < 2:
        pytest.skip('needs multi-device mesh')
    tokens, mask = _data()
    params = quantize_params(init_params(CFG, jax.random.PRNGKey(0)), CFG)
    ref = np.asarray(forward(params, CFG, tokens, mask, use_flash=False))

    from opencompass_tpu.nn import shard_params
    from opencompass_tpu.parallel import MeshSpec, make_mesh, use_mesh
    mesh = make_mesh(MeshSpec(data=1, model=2, seq=1))
    with use_mesh(mesh):
        sp = shard_params(params, CFG, mesh)
        got = np.asarray(jax.jit(
            lambda p, t, m: forward(p, CFG, t, m, use_flash=False))(
                sp, tokens, mask))
    np.testing.assert_allclose(ref, got, rtol=2e-2, atol=2e-2)


def test_int8_kv_cache_decode_close_to_fp():
    import dataclasses
    from opencompass_tpu.nn import init_cache, prefill
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0,
                                CFG.vocab_size)
    mask = jnp.ones((2, 12), bool)
    params = init_params(CFG, jax.random.PRNGKey(0))
    cfgq = dataclasses.replace(CFG, kv_quant=True)

    logits_fp, _, _ = prefill(params, CFG, tokens, mask,
                              init_cache(CFG, 2, 20))
    logits_q, cache, _ = prefill(params, cfgq, tokens, mask,
                                 init_cache(cfgq, 2, 20))
    assert cache['k'].dtype == jnp.int8 and 'ks' in cache
    ref, got = np.asarray(logits_fp), np.asarray(logits_q)
    denom = np.maximum(np.abs(ref).max(), 1e-6)
    assert np.abs(ref - got).max() / denom < 0.05


def test_int8_kv_greedy_generate_runs_and_tracks():
    import dataclasses
    cfgq = dataclasses.replace(CFG, kv_quant=True)
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens, mask = _data(B=2, S=8)
    out_fp, _ = jax.jit(lambda p, t, m: greedy_generate(p, CFG, t, m, 8))(
        params, tokens, mask)
    out_q, _ = jax.jit(lambda p, t, m: greedy_generate(p, cfgq, t, m, 8))(
        params, tokens, mask)
    assert out_q.shape == (2, 8)
    # greedy argmax on a random tiny model: most steps should agree
    agree = (np.asarray(out_fp) == np.asarray(out_q)).mean()
    assert agree >= 0.5, f'int8 KV diverged too much: agree={agree}'


def test_jaxlm_int8_kv_end_to_end():
    lm = JaxLM(config='tiny', max_seq_len=128, quantize='int8-kv')
    assert lm.cfg.kv_quant
    out = lm.generate(['hello world'], max_out_len=6)
    assert len(out) == 1
    nll = lm.get_ppl(['scoring path unaffected'])
    assert np.isfinite(nll[0])


def test_w8a8_forward_close_to_fp():
    cfga = dataclasses.replace(CFG, act_quant=True)
    params = init_params(CFG, jax.random.PRNGKey(0))
    qparams = quantize_params(params, CFG)
    tokens, mask = _data()
    ref = np.asarray(forward(params, CFG, tokens, mask, use_flash=False))
    got = np.asarray(forward(qparams, cfga, tokens, mask, use_flash=False))
    denom = np.maximum(np.abs(ref).max(), 1e-6)
    # dynamic per-token int8 activations on top of int8 weights: a little
    # looser than weight-only, still tracking
    assert np.abs(ref - got).max() / denom < 0.08
    nll_ref = np.asarray(sequence_nll(jnp.asarray(ref), tokens, mask))
    nll_got = np.asarray(sequence_nll(jnp.asarray(got), tokens, mask))
    np.testing.assert_allclose(nll_got, nll_ref, rtol=0.05)


def test_w8a8_ppl_ranking_agrees_with_bf16():
    """The PPL-mode eval contract is argmin over choices: W8A8 scoring must
    rank a tiny model's choices like the full-precision path."""
    lm_q = JaxLM(config='tiny', max_seq_len=128, quantize='w8a8')
    lm_fp = JaxLM(config='tiny', max_seq_len=128)
    choices = ['the answer is yes', 'the answer is no',
               'the answer is maybe', 'completely different text here']
    nll_q = lm_q.get_ppl(choices)
    nll_fp = lm_fp.get_ppl(choices)
    assert np.argmin(nll_q) == np.argmin(nll_fp)
    np.testing.assert_allclose(nll_q, nll_fp, rtol=0.08)


def test_int4_kv_decode_logit_envelope():
    """Retired xfail (the blanket token-agreement mark): int4
    per-vector RTN KV is inherently too coarse for greedy argmax on a
    RANDOM tiny model — measured ~18% prefill logit error against
    2-7% fp argmax margins, so token agreement vs the fp path is
    quantization noise, not a testable contract (real-model accuracy
    is gated by tools/quant_agreement.py).  What DOES hold strictly —
    and what the engine's int4-KV eligibility rests on — is a logit
    ERROR ENVELOPE on the decode path: driving the paged engine step
    (the continuous engine's kernel) teacher-forced over a prefill
    chunk plus decode steps, every int4-KV step's logits stay within
    the measured envelope (~18%, bound 0.3 with slack) of the fp
    path's."""
    from opencompass_tpu.nn.paged_kv import (PageAllocator, PageTable,
                                             init_page_pool,
                                             pages_per_seq)
    from opencompass_tpu.nn.transformer import paged_step
    cfgq = dataclasses.replace(CFG, kv_quant='int4')
    params = init_params(CFG, jax.random.PRNGKey(0))
    page, max_new = 8, 6
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(1, CFG.vocab_size, n)) for n in (6, 11)]
    mp = pages_per_seq(max(len(p) for p in prompts) + max_new, page)

    def drive(cfg):
        pool = init_page_pool(cfg, 1 + len(prompts) * mp, page)
        alloc = PageAllocator(1 + len(prompts) * mp)
        table = PageTable(len(prompts), mp)
        kv = [0] * len(prompts)
        for s, ids in enumerate(prompts):
            table.assign(s, alloc.alloc(
                pages_per_seq(len(ids) + max_new, page)))
        step = jax.jit(lambda pr, pl, t, st, nn_, pt: paged_step(
            pr, cfg, t, st, nn_, pt, pl, page))
        out = []
        # teacher-forced: both variants consume the SAME token stream
        # (prompt then fixed probe tokens), isolating per-step logit
        # error from autoregressive divergence
        for turn in range(max(len(p) for p in prompts) // page + 1
                          + max_new):
            prefilling = any(kv[s] < len(p)
                             for s, p in enumerate(prompts))
            t = page if prefilling else 1
            toks = np.zeros((len(prompts), t), np.int32)
            start = np.zeros((len(prompts),), np.int32)
            n_new = np.zeros((len(prompts),), np.int32)
            for s, ids in enumerate(prompts):
                if prefilling:
                    if kv[s] < len(ids):
                        chunk = ids[kv[s]:kv[s] + t]
                        toks[s, :len(chunk)] = chunk
                        start[s] = kv[s]
                        n_new[s] = len(chunk)
                else:
                    toks[s, 0] = (s + 3 * turn) % CFG.vocab_size
                    start[s] = kv[s]
                    n_new[s] = 1
            logits, pool = step(params, pool, jnp.asarray(toks),
                                jnp.asarray(start), jnp.asarray(n_new),
                                jnp.asarray(table.table))
            out.append(np.asarray(logits))
            for s in range(len(prompts)):
                kv[s] += int(n_new[s])
        return out

    fp, q4 = drive(CFG), drive(cfgq)
    assert len(fp) == len(q4) and len(fp) > 2
    for step_fp, step_q in zip(fp, q4):
        denom = np.maximum(np.abs(step_fp).max(), 1e-6)
        assert np.abs(step_fp - step_q).max() / denom < 0.3


def test_int4_kv_prefill_logits_bounded():
    """The strict part of the int4-KV contract that DOES hold on random
    weights: prefill logits stay within a measured error envelope of the
    fp path (~18% of logit scale; bound set at 0.3 for slack), and the
    cache really is int4."""
    cfgq = dataclasses.replace(CFG, kv_quant='int4')
    from opencompass_tpu.nn import init_cache, prefill
    tokens, mask = _data(B=2, S=8)
    params = init_params(CFG, jax.random.PRNGKey(0))
    cache = init_cache(cfgq, 2, 16)
    assert cache['k'].dtype == jnp.int4
    logits_fp, _, _ = prefill(params, CFG, tokens, mask,
                              init_cache(CFG, 2, 16))
    logits_q, _, _ = prefill(params, cfgq, tokens, mask, cache)
    ref, got = np.asarray(logits_fp), np.asarray(logits_q)
    denom = np.maximum(np.abs(ref).max(), 1e-6)
    assert np.abs(ref - got).max() / denom < 0.3


def test_jaxlm_w8a8_kv4_end_to_end():
    lm = JaxLM(config='tiny', max_seq_len=128, quantize='w8a8-kv4')
    assert lm.cfg.kv_quant_mode == 'int4' and lm.cfg.act_quant
    out = lm.generate(['hello world'], max_out_len=6)
    assert len(out) == 1
    nll = lm.get_ppl(['scoring path quantized but finite'])
    assert np.isfinite(nll[0])


def test_quantize_mode_validation():
    with pytest.raises(ValueError):
        JaxLM(config='tiny', quantize='int4')  # int4 weights: not a mode
    with pytest.raises(ValueError):
        JaxLM(config='tiny', quantize='w8a8-kv2')
    with pytest.raises(NotImplementedError):
        JaxLM(config='tiny', quantize='w4a8',
              parallel=dict(data=1, model=2), tokenizer_only=True)


def test_int4x2_pack_roundtrip():
    """Packing then unpacking restores the quantized int4 grid exactly,
    for both storage orientations."""
    from opencompass_tpu.nn.quant import GROUP, _pack_int4x2
    from opencompass_tpu.nn.transformer import _unpack_int4x2
    rng = np.random.RandomState(0)
    w = rng.randn(2 * GROUP, 3 * GROUP).astype(np.float32)  # (in, out)
    packed, s = _pack_int4x2(w, axis=-2, xp=np)
    assert packed.dtype == np.uint8
    assert packed.shape == (3 * GROUP, GROUP)        # NT, halved
    assert s.shape == (3 * GROUP, 2)                 # (out, groups)
    w8 = np.asarray(_unpack_int4x2(jnp.asarray(packed)))
    assert w8.min() >= -7 and w8.max() <= 7
    # dequantized reconstruction ~ original within one int4 step/group
    recon = (w8.reshape(3 * GROUP, 2, GROUP).astype(np.float32)
             * s[:, :, None]).reshape(3 * GROUP, 2 * GROUP).T
    step = np.repeat(s.T, GROUP, axis=0).reshape(2 * GROUP, 3 * GROUP)
    assert np.all(np.abs(recon - w) <= step / 2 + 1e-6)
    # NT orientation input packs without the transpose
    packed_nt, s_nt = _pack_int4x2(w.T.copy(), axis=-1, xp=np)
    np.testing.assert_array_equal(packed, packed_nt)
    np.testing.assert_array_equal(s, s_nt)


def test_w4a8_forward_tracks_fp():
    """int4x2 weights with group scales keep the forward usable: logits
    correlate with full precision and the NLL ranking survives (group
    RTN int4 is coarser than int8 — tolerances reflect that)."""
    cfg128 = TransformerConfig.llama(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        num_kv_heads=4, intermediate_size=256, max_seq_len=64,
        dtype='float32')
    cfga = dataclasses.replace(cfg128, act_quant=True)
    params = init_params(cfg128, jax.random.PRNGKey(0))
    q4 = quantize_params(params, cfg128, mode='int4x2')
    assert q4['layers']['q']['w'].dtype == jnp.uint8
    assert q4['layers']['down']['w'].dtype == jnp.uint8
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 24), 0, 512)
    mask = jnp.ones((4, 24), bool)
    ref = np.asarray(forward(params, cfg128, tokens, mask,
                             use_flash=False))
    got = np.asarray(forward(q4, cfga, tokens, mask, use_flash=False))
    assert np.all(np.isfinite(got))
    # group-RTN int4 on random gaussian weights is the worst case (no
    # outlier structure to hide behind): correlation, not closeness, is
    # the hermetic bar — cross-precision eval agreement at real geometry
    # is measured by tools/quant_agreement.py --quant w4a8-kv4
    cos = np.dot(ref.ravel(), got.ravel()) / (
        np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > 0.9, f'w4a8 decorrelated: cos={cos}'
    # per-sample NLL shift stays small (argmin over 4 i.i.d. random
    # sequences is a statistical tie at this scale — see nn/agreement.py
    # on tie bands — so the bar is the NLL shift, not the tie-break)
    nll_ref = np.asarray(sequence_nll(jnp.asarray(ref), tokens, mask))
    nll_got = np.asarray(sequence_nll(jnp.asarray(got), tokens, mask))
    assert np.all(np.abs(nll_got - nll_ref) / nll_ref < 0.02)


def test_w4a8_decode_runs_and_tracks():
    cfg128 = TransformerConfig.llama(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        num_kv_heads=4, intermediate_size=256, max_seq_len=64,
        dtype='float32')
    cfg_hl = dataclasses.replace(cfg128, act_quant=True, kv_quant='int4')
    params = init_params(cfg128, jax.random.PRNGKey(0))
    q4 = quantize_params(params, cfg128, mode='int4x2')
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 512)
    mask = jnp.ones((2, 8), bool)
    out_q, _ = jax.jit(lambda p, t, m: greedy_generate(
        p, cfg_hl, t, m, 8))(q4, tokens, mask)
    assert out_q.shape == (2, 8)
    # wiring check (free-running cross-precision agreement on a tiny
    # random model is chaos, not signal): the packed decode path's first
    # token must equal the packed parallel forward's argmax — prefill,
    # cache, and _packed_matmul all agree with each other
    logits_q = forward(q4, cfg_hl, tokens, mask, use_flash=False)
    first = np.asarray(jnp.argmax(logits_q[:, -1], -1))
    assert (np.asarray(out_q)[:, 0] == first).all()


def test_jaxlm_w4a8_kv4_end_to_end():
    lm = JaxLM(config=dict(preset='llama', vocab_size=512,
                           hidden_size=128, num_layers=2, num_heads=4,
                           num_kv_heads=4, intermediate_size=256,
                           max_seq_len=128),
               max_seq_len=128, quantize='w4a8-kv4')
    assert lm.cfg.act_quant and lm.cfg.kv_quant_mode == 'int4'
    assert lm.params['layers']['q']['w'].dtype == jnp.uint8
    out = lm.generate(['hello world'], max_out_len=6)
    assert len(out) == 1
    nll = lm.get_ppl(['finite scoring please'])
    assert np.isfinite(nll[0])


def test_int4_weight_quantize_forward_close():
    """int4 weights at the quantize_params level (CPU backend accepts int4
    jit arguments; JaxLM gates the mode off on TPU — see nn/quant.py)."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    q4 = quantize_params(params, CFG, mode='int4')
    assert q4['layers']['q']['w'].dtype == jnp.int4
    tokens, mask = _data()
    ref = np.asarray(forward(params, CFG, tokens, mask,
                             use_flash=False)).ravel()
    got = np.asarray(forward(q4, CFG, tokens, mask,
                             use_flash=False)).ravel()
    # 4-bit per-channel scales are coarse on random gaussian weights (a
    # production int4 recipe would add group-wise scales); this pins the
    # storage/compute pipeline, not a shipped accuracy tier — the shipped
    # int4 config is the KV cache, whose per-vector scales are tested
    # above by decode token agreement
    assert np.all(np.isfinite(got))
    cos = np.dot(ref, got) / (np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > 0.9, f'int4 forward decorrelated: cos={cos}'


def test_kv_quant_mode_validation():
    bad = dataclasses.replace(CFG, kv_quant='int2')
    with pytest.raises(ValueError):
        bad.kv_quant_mode
    assert dataclasses.replace(CFG, kv_quant=True).kv_quant_mode == 'int8'


def test_agreement_stats_math():
    """Hermetic unit test of nn/agreement.py's stat functions."""
    from opencompass_tpu.nn.agreement import gen_stats, scoring_stats
    # two items, 2 choices: item 0 decided + agreeing, item 1 a tie flip
    nll_fp = np.array([1.0, 2.0, 1.0, 1.0001])
    nll_q = np.array([1.001, 2.001, 1.0002, 1.0001])
    s = scoring_stats(nll_fp, nll_q, choices=2)
    assert s['n_items'] == 2 and s['n_decided_items'] == 1
    assert s['decided_top1_agreement'] == 1.0
    assert s['top1_agreement'] == 0.5
    assert s['max_flip_margin'] < 0.005  # the flip was a statistical tie
    g = gen_stats(np.array([[1, 2, 3, 4]]), np.array([[1, 2, 9, 9]]))
    assert g['token_match_rate'] == 0.5
    assert g['identical_seq_frac'] == 0.0
    assert g['mean_first_divergence_step'] == 2.0


def test_forced_decode_self_consistency_tiny():
    """Teacher-forcing the model's own greedy output through the decode
    path reproduces it (per-step argmax == forced token) on the CPU mesh,
    where the math is bit-stable."""
    from opencompass_tpu.nn.agreement import forced_decode
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens, mask = _data(B=2, S=8)
    out, _ = jax.jit(
        lambda p, t, m: greedy_generate(p, CFG, t, m, 8))(params, tokens,
                                                          mask)
    lp, am, margin, rank = forced_decode(params, CFG, tokens, mask, out)
    assert am.shape == out.shape == rank.shape
    assert (np.asarray(am) == np.asarray(out)).all()
    assert (np.asarray(rank) == 0).all()
    assert np.all(np.asarray(margin) >= 0)
    assert np.all(np.isfinite(np.asarray(lp)))


def test_forced_decode_alibi_runs():
    """forced_decode mirrors greedy_generate's kv_positions carry for
    ALiBi models (it raised without it)."""
    from opencompass_tpu.nn.agreement import forced_decode
    cfg = dataclasses.replace(CFG, positional='alibi')
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, mask = _data(B=2, S=8)
    out, _ = jax.jit(
        lambda p, t, m: greedy_generate(p, cfg, t, m, 4))(params, tokens,
                                                          mask)
    lp, am, margin, rank = forced_decode(params, cfg, tokens, mask, out)
    assert (np.asarray(am) == np.asarray(out)).all()
    assert np.all(np.isfinite(np.asarray(lp)))


@pytest.mark.slow
def test_w8a8_agreement_at_7b_geometry_on_tpu():
    """VERDICT r03 #1: the headline's quantized recipes (W8A8 scoring,
    W8A8+int4-KV decode) must preserve eval semantics at FULL 7B geometry
    (4096x32) on the real chip, not just at 512x4.  Runs
    tools/quant_agreement.py in a TPU subprocess (~2 min; the committed
    record lives in QUANT_AGREEMENT_7B.json and next to the headline in
    BENCH_r04.json's detail.quant_agreement)."""
    import json
    import subprocess
    axon = os.environ.get('OC_TPU_AXON_IPS')
    if not axon:
        pytest.skip('no TPU plugin config in environment')
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = axon
    env.pop('JAX_PLATFORMS', None)
    env.pop('XLA_FLAGS', None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, 'tools', 'quant_agreement.py'),
         '--geometry', '7b'],
        capture_output=True, text=True, timeout=1800, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout)
    s = rec['scoring_w8a8_vs_bf16']
    # items whose bf16 margin exceeds the tie band must rank identically
    assert s['decided_top1_agreement'] >= 0.97, s
    # per-sample NLL shift well under 1% (VERDICT's done criterion)
    assert s['median_rel_dnll'] < 0.01, s
    assert s['p95_rel_dnll'] < 0.01, s
    # any argmin flips are confined to statistical ties
    assert s['max_flip_margin'] < 0.005, s
    f = rec['forced_decode_w8a8kv8_vs_bf16']
    # where the bf16 model is decisive, the quantized decode picks the
    # same token at (at least) the bf16 self-consistency rate minus noise
    if f['n_decided_steps'] >= 20:
        assert f['decided_step_agreement'] >= 0.9, f
    assert f['median_quant_rank_of_bf16_choice'] <= 5, f


@pytest.mark.slow
def test_w8a8_ranking_agreement_at_scale():
    """Stronger accuracy evidence for the W8A8 headline: at llama-512x4
    scale, quantized scoring must rank a pool of candidate completions
    like the full-precision (fp32 here, for bit-stable CPU math) path:
    top choice identical, full ranking nearly so."""
    cfg = TransformerConfig.llama(
        vocab_size=2048, hidden_size=512, num_layers=4, num_heads=8,
        num_kv_heads=8, intermediate_size=1408, max_seq_len=128,
        dtype='float32')
    cfga = dataclasses.replace(cfg, act_quant=True)
    params = init_params(cfg, jax.random.PRNGKey(1))
    qparams = quantize_params(params, cfg)
    key = jax.random.PRNGKey(5)
    tokens = jax.random.randint(key, (16, 24), 0, cfg.vocab_size)
    mask = jnp.ones((16, 24), bool)
    nll_fp = np.asarray(sequence_nll(
        forward(params, cfg, tokens, mask, use_flash=False), tokens, mask))
    nll_q = np.asarray(sequence_nll(
        forward(qparams, cfga, tokens, mask, use_flash=False), tokens,
        mask))
    assert np.argmin(nll_q) == np.argmin(nll_fp)
    # rank correlation over the candidate pool stays near-perfect
    rank_fp = np.argsort(np.argsort(nll_fp))
    rank_q = np.argsort(np.argsort(nll_q))
    corr = np.corrcoef(rank_fp, rank_q)[0, 1]
    assert corr > 0.95, f'rank correlation degraded: {corr}'
    np.testing.assert_allclose(nll_q, nll_fp, rtol=0.05)
