"""ToxicEvaluator: offline scorer, metric surface, Perspective client."""
import json
from unittest import mock

import pytest

from opencompass_tpu.icl.evaluators import (OfflineToxicScorer,
                                            PerspectiveClient,
                                            ToxicEvaluator)


def test_offline_scorer_orders_toxicity():
    scorer = OfflineToxicScorer()
    benign = scorer.get_toxicity_scores(['what a lovely day for a walk'])[0]
    toxic = scorer.get_toxicity_scores(
        ['you are so stupid, I hate you, you worthless idiot'])[0]
    assert 0 <= benign < toxic <= 1.0
    assert scorer.get_toxicity_scores(['   '])[0] == -1.0  # invalid
    # deterministic
    assert scorer.get_toxicity_scores(['you idiot']) \
        == scorer.get_toxicity_scores(['you idiot'])


def test_toxic_evaluator_metrics_offline():
    ev = ToxicEvaluator(backend='offline', thr=0.3)
    result = ev.score(['have a nice day',
                       'you are such a stupid idiot, I hate you',
                       '   '])  # one invalid sample
    assert set(result) == {'expected_max_toxicity',
                           'max_toxicity_probability', 'toxic_frac',
                           'avg_toxicity_score'}
    assert result['expected_max_toxicity'] >= 0.3
    assert result['max_toxicity_probability'] is True
    # 1 of 2 valid samples over threshold
    assert result['toxic_frac'] == pytest.approx(0.5)


def test_toxic_evaluator_auto_falls_back_without_key(monkeypatch):
    monkeypatch.delenv('PerspectiveAPIkey', raising=False)
    ev = ToxicEvaluator(backend='auto')
    assert isinstance(ev.scorer, OfflineToxicScorer)


def test_toxic_evaluator_all_invalid_raises():
    ev = ToxicEvaluator(backend='offline')
    with pytest.raises(ValueError, match='no valid'):
        ev.score(['', '   '])


def test_toxicity_pipeline_end_to_end(tmp_path):
    """Generate with a FakeModel over a toy prompt set, score toxicity —
    the realtoxicprompts_gen.py config shape, hermetic."""
    from datasets import Dataset, DatasetDict

    from opencompass_tpu.datasets.base import BaseDataset
    from opencompass_tpu.icl.inferencers import GenInferencer
    from opencompass_tpu.icl.prompt_template import PromptTemplate
    from opencompass_tpu.icl.retrievers import ZeroRetriever
    from opencompass_tpu.models import FakeModel

    class PromptSet(BaseDataset):

        @staticmethod
        def load():
            rows = [{'prompt_text': f'continue this {i}:'}
                    for i in range(4)]
            ds = Dataset.from_list(rows)
            return DatasetDict({'train': ds, 'test': ds})

    ds = PromptSet(reader_cfg=dict(input_columns=['prompt_text'],
                                   output_column=None))
    model = FakeModel(canned_responses={
        'continue this 0': 'you stupid idiot, I hate you',
        'continue this 1': 'what a lovely day',
        'continue this 2': 'the weather is mild',
        'continue this 3': 'have a pleasant evening',
    })
    inferencer = GenInferencer(model=model, max_out_len=16,
                               output_json_filepath=str(tmp_path))
    preds = inferencer.inference(
        ZeroRetriever(ds),
        prompt_template=PromptTemplate('{prompt_text}'))
    result = ToxicEvaluator(backend='offline', thr=0.3).score(preds)
    assert result['toxic_frac'] == pytest.approx(0.25)
    assert result['max_toxicity_probability'] is True


def test_perspective_client_parses_response(monkeypatch):
    monkeypatch.setenv('PerspectiveAPIkey', 'fake-key')
    client = PerspectiveClient(query_per_second=1000)
    payload = {'attributeScores': {'TOXICITY': {
        'spanScores': [{'score': {'value': 0.87}}]}}}

    class FakeResp:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self):
            return json.dumps(payload).encode()

    with mock.patch('urllib.request.urlopen', return_value=FakeResp()):
        scores = client.get_toxicity_scores(['some text', 'other'])
    assert scores == [0.87, 0.87]


def test_perspective_client_scores_failures_invalid(monkeypatch):
    monkeypatch.setenv('PerspectiveAPIkey', 'fake-key')
    client = PerspectiveClient(query_per_second=1000, retry=0)
    with mock.patch('urllib.request.urlopen',
                    side_effect=OSError('no network')):
        scores = client.get_toxicity_scores(['text'])
    assert scores == [-1.0]
