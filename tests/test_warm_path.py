"""Warm-path execution: persistent compile cache, model-resident
workers, planned-shape warm-up, persisted token-length cache."""
import json
import os
import os.path as osp
import sys

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


# -- wire protocol ---------------------------------------------------------

def test_worker_frame_roundtrip():
    from opencompass_tpu.runners.worker import (WorkerError, read_frame,
                                                write_frame)
    r, w = os.pipe()
    with os.fdopen(w, 'wb') as wf:
        write_frame(wf, {'cmd': 'run', 'x': [1, 2, 3]})
        write_frame(wf, {'cmd': 'shutdown'})
    assert read_frame(r) == {'cmd': 'run', 'x': [1, 2, 3]}
    assert read_frame(r) == {'cmd': 'shutdown'}
    with pytest.raises(WorkerError):
        read_frame(r)  # EOF
    os.close(r)


def test_worker_request_watched_kills_stalled_worker():
    """A worker that never answers and shows no liveness is killed
    after stall_timeout (the one-shot watchdog's semantics, ported)."""
    import subprocess

    from opencompass_tpu.runners.worker import WorkerError, WorkerHandle
    handle = WorkerHandle.__new__(WorkerHandle)
    handle._log_fh = open(os.devnull, 'a')
    handle.proc = subprocess.Popen(
        [sys.executable, '-c', 'import time; time.sleep(60)'],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=handle._log_fh, start_new_session=True)
    handle.dead = False
    with pytest.raises(WorkerError, match='wedged|died'):
        handle.request_watched({'cmd': 'run'}, stall_timeout=1.0,
                               liveness=lambda: None, poll=0.2)
    assert handle.dead
    assert handle.proc.poll() is not None


def test_worker_read_timeout():
    from opencompass_tpu.runners.worker import WorkerError, read_frame
    r, w = os.pipe()
    try:
        with pytest.raises(WorkerError, match='timed out'):
            read_frame(r, timeout=0.2)
    finally:
        os.close(r)
        os.close(w)


# -- eligibility / grouping ------------------------------------------------

def _demo_tasks(tmp_path, max_task_size=100, datasets=None):
    from opencompass_tpu.config import Config
    from opencompass_tpu.partitioners import SizePartitioner
    cfg = Config.fromfile(osp.join(REPO, 'configs/eval_demo.py'))
    cfg['work_dir'] = str(tmp_path / 'run')
    if datasets is not None:
        cfg['datasets'] = [d for d in cfg['datasets']
                           if d['abbr'] in datasets]
    part = SizePartitioner(str(tmp_path / 'run' / 'predictions'),
                           max_task_size=max_task_size,
                           dataset_size_path=str(tmp_path / 'size.json'))
    return cfg, part(cfg)


def test_partitioner_stamps_model_key(tmp_path):
    _, tasks = _demo_tasks(tmp_path)
    keys = {t['model_key'] for t in tasks}
    assert len(keys) == 1 and all(keys)  # one model -> one affinity key


def test_worker_grouping_modes(tmp_path):
    from opencompass_tpu.runners import LocalRunner
    _, tasks = _demo_tasks(tmp_path)

    def plan(**kw):
        r = LocalRunner(task=dict(type='OpenICLInferTask'), **kw)
        return r._plan_worker_groups(tasks)

    groups, singles = plan(use_workers=False)
    assert not groups and len(singles) == len(tasks)
    # auto: FakeModel tasks are chipless -> stay one-shot
    groups, singles = plan()
    assert not groups and len(singles) == len(tasks)
    # explicit: all tasks share one model -> one worker group, in order
    groups, singles = plan(use_workers=True)
    assert not singles and len(groups) == 1
    assert groups[0][1] == list(range(len(tasks)))


def test_api_models_never_worker_eligible():
    from opencompass_tpu.runners.worker import task_worker_eligible
    api_task = {'models': [dict(type='OpenAI', path='gpt-4')],
                'datasets': [[]], 'work_dir': '.'}
    assert not task_worker_eligible(api_task)


# -- worker pool end to end ------------------------------------------------

def _run_worker_pool(tmp_path, n_expected_tasks, env=None, retry=0):
    from opencompass_tpu import obs
    from opencompass_tpu.runners import LocalRunner
    cfg, tasks = _demo_tasks(tmp_path, max_task_size=160,
                             datasets={'demo-gen'})
    assert len(tasks) == n_expected_tasks
    work = cfg['work_dir']
    os.makedirs(work, exist_ok=True)
    old_env = {}
    for k, v in (env or {}).items():
        old_env[k] = os.environ.get(k)
        os.environ[k] = v
    obs.reset_obs()
    tracer = obs.init_obs(work, enabled=True)
    try:
        runner = LocalRunner(task=dict(type='OpenICLInferTask'),
                             use_workers=True, max_num_workers=4,
                             retry=retry)
        status = runner(tasks)
    finally:
        tracer.close()
        obs.reset_obs()
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    events = [json.loads(line)
              for line in open(osp.join(work, 'obs/events.jsonl'))]
    return work, tasks, status, events


def test_worker_pool_end_to_end(tmp_path):
    """Two dataset shards through one resident worker: exactly one
    model construction, in-order green results, predictions written,
    heartbeats still flowing."""
    work, tasks, status, events = _run_worker_pool(tmp_path, 2)
    # in-order, all green
    assert [rc for _, rc in status] == [0, 0]
    expected = [t['datasets'][0][0]['abbr'] for t in tasks]
    assert [name for name, _ in status] == \
        [f'OpenICLInfer[fake-demo/{a}]' for a in expected]
    # exactly one model build; the second shard reused it
    builds = [e for e in events if e.get('name') == 'worker_model_build']
    reuses = [e for e in events if e.get('name') == 'worker_model_reuse']
    assert len(builds) == 1
    assert reuses
    # outputs on disk (the completion contract)
    preds = sorted(os.listdir(osp.join(work, 'predictions/fake-demo')))
    assert preds == [f'{a}.json' for a in expected]
    # heartbeats flowed from inside the worker, one file per task
    hb_files = os.listdir(osp.join(work, 'obs/progress'))
    assert len(hb_files) == 2
    for f in hb_files:
        hb = json.load(open(osp.join(work, 'obs/progress', f)))
        assert hb['state'] == 'done'


def test_worker_crash_falls_back_to_subprocess(tmp_path):
    """A worker crash mid-group must not lose the task: the runner falls
    back to the one-shot subprocess path and the run stays green."""
    work, tasks, status, events = _run_worker_pool(
        tmp_path, 2, env={'OCT_WORKER_FAULT': 'crash:demo-gen_1'})
    assert [rc for _, rc in status] == [0, 0]
    fallbacks = [e for e in events if e.get('name') == 'worker_fallback']
    assert len(fallbacks) == 1
    preds = sorted(os.listdir(osp.join(work, 'predictions/fake-demo')))
    assert preds == ['demo-gen_0.json', 'demo-gen_1.json']


# -- persistent compile cache ----------------------------------------------

def test_compile_cache_counters_and_manifest(tmp_path, monkeypatch):
    """Cold build pays cache misses; a rebuilt model after
    jax.clear_caches() deserializes from the persistent cache (hits in
    the perf record, compile_seconds under the cold figure) and the
    sidecar shape manifest knows the dispatched shape."""
    import jax
    from opencompass_tpu.models.jax_lm import JaxLM
    from opencompass_tpu.utils import compile_cache
    from opencompass_tpu.utils.perf import TaskProfiler
    cache_dir = str(tmp_path / 'xla')
    monkeypatch.setenv('OCT_COMPILE_CACHE', cache_dir)
    monkeypatch.setattr(compile_cache, '_enabled_dir', None)
    assert compile_cache.enable() == osp.abspath(cache_dir)
    # earlier tests in a full-suite run may have compiled the tiny
    # model's shapes into jax's in-memory executable cache, which would
    # serve the "cold" pass without ever consulting the persistent
    # cache — start genuinely cold
    jax.clear_caches()

    def one_pass():
        lm = JaxLM(config='tiny', max_seq_len=128)
        with TaskProfiler(lm) as prof:
            lm.get_ppl(['hello warm world'])
        return lm, prof.record

    lm1, cold = one_pass()
    assert cold['compile_cache_misses'] > 0
    assert cold['compile_cache_hits'] == 0
    jax.clear_caches()
    _, warm = one_pass()
    assert warm['compile_cache_hits'] > 0
    assert warm['compile_cache_misses'] == 0
    assert warm['compile_seconds'] < cold['compile_seconds']
    # the manifest recorded the dispatched ppl shape with its seconds
    manifest = compile_cache.load_manifest(cache_dir)
    sig = lm1.shape_signature
    assert sig in manifest
    assert any(k.startswith('ppl:') for k in manifest[sig])


def test_shape_manifest_probe(tmp_path):
    from opencompass_tpu.utils import compile_cache
    cache_dir = str(tmp_path / 'xla')
    compile_cache.record_shape('sig1', 'gen', (4, 128), 120.0,
                               cache_dir=cache_dir)
    compile_cache.record_shape('sig1', 'ppl', (8, 256), 60.0,
                               cache_dir=cache_dir)
    # slower observation wins (cold compile vs later cache-served call)
    compile_cache.record_shape('sig1', 'gen', (4, 128), 1.0,
                               cache_dir=cache_dir)
    manifest = compile_cache.load_manifest(cache_dir)
    assert manifest['sig1']['gen:4x128'] == 120.0
    probe = compile_cache.probe_shapes(
        'sig1', ['gen:4x128', 'gen:8x128'], cache_dir)
    assert probe['n_warm'] == 1 and probe['n_cold'] == 1
    assert probe['warm'] == ['gen:4x128']
    assert probe['est_warm_startup_s'] < probe['est_cold_startup_s']
    # unknown signature: everything cold
    probe2 = compile_cache.probe_shapes('other', ['gen:4x128'], cache_dir)
    assert probe2['n_warm'] == 0 and probe2['n_cold'] == 1


def test_cli_plan_cache_dir_probe(tmp_path):
    """`cli plan --cache-dir` joins the planner census against the
    manifest: a manifest seeded with the planned shapes reports them
    warm."""
    from opencompass_tpu.config import Config
    from opencompass_tpu.utils import compile_cache
    from opencompass_tpu.utils.build import build_model_from_cfg
    from opencompass_tpu.utils.plan_preview import main as plan_main
    from opencompass_tpu.utils.plan_preview import shape_census

    mcfg = Config.fromfile(
        osp.join(REPO, 'configs/models/jax_llama_tiny.py'))
    model_cfg = dict(mcfg['models'][0])
    model_cfg['tokenizer_only'] = True
    cfg_path = tmp_path / 'plan_cfg.py'
    cfg = Config.fromfile(osp.join(REPO, 'configs/eval_demo.py'))
    cfg['models'] = [model_cfg]
    cfg.dump(str(cfg_path))

    # seed the manifest with exactly the census shapes
    model = build_model_from_cfg(model_cfg)
    cache_dir = str(tmp_path / 'xla')
    n_seeded = 0
    for ds in cfg['datasets']:
        for spec in shape_census(model, model_cfg, ds):
            compile_cache.record_shape(
                model.shape_signature, spec['kind'],
                (spec['b'], spec['s']), 42.0, cache_dir=cache_dir)
            n_seeded += 1
    assert n_seeded > 0

    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = plan_main([str(cfg_path), '--cache-dir', cache_dir])
    out = buf.getvalue()
    assert rc == 0
    assert 'compile-cache probe' in out
    assert 'warm' in out
    # every planned shape was seeded -> no task may report cold shapes
    assert ' 0 warm' not in out


# -- persisted token-length cache ------------------------------------------

def test_toklen_cache_roundtrip_and_bound(tmp_path):
    from collections import OrderedDict

    from opencompass_tpu.utils import toklen_cache
    d = str(tmp_path / 'toklen')
    lengths = OrderedDict((bytes([i]) * 16, i) for i in range(10))
    toklen_cache.save(d, 'abc123', lengths, max_entries=4)
    loaded = toklen_cache.load(d, 'abc123')
    assert list(loaded.values()) == [6, 7, 8, 9]  # newest 4 kept
    assert toklen_cache.load(d, 'missing') == OrderedDict()


def test_jaxlm_persists_token_lengths(tmp_path, monkeypatch):
    """A second JaxLM process-alike starts with the first one's token
    lengths preloaded (no re-tokenization on resume/retry)."""
    from opencompass_tpu.models.jax_lm import JaxLM
    monkeypatch.setenv('OCT_CACHE_ROOT', str(tmp_path / 'cache'))
    lm = JaxLM(config='tiny', max_seq_len=128, tokenizer_only=True)
    n = lm.get_token_len('a prompt worth remembering')
    lm.save_caches()
    path = osp.join(str(tmp_path / 'cache'), 'toklen',
                    f'{lm._toklen_digest}.json')
    assert osp.exists(path)
    lm2 = JaxLM(config='tiny', max_seq_len=128, tokenizer_only=True)
    key = lm2._cache_key('a prompt worth remembering')
    assert lm2._token_len_cache.get(key) == n


def test_cli_plumbs_use_workers():
    """--workers/--no-workers reach LocalRunner via the config."""
    import types

    from opencompass_tpu.cli import _build_runner, get_config_from_arg
    args = types.SimpleNamespace(slurm=False, dlc=False, debug=False,
                                 max_num_workers=4, partition=None,
                                 quotatype=None, retry=0, num_devices=None,
                                 work_dir=None, lark=False, profile=False,
                                 obs=False, obs_port=None,
                                 config=osp.join(
                                     REPO, 'configs/eval_demo.py'),
                                 use_workers=False)
    cfg = get_config_from_arg(args)
    assert cfg['use_workers'] is False
    runner = _build_runner('OpenICLInferTask', args, cfg)
    assert runner.use_workers is False
    args.use_workers = None  # default: auto
    cfg2 = get_config_from_arg(args)
    assert 'use_workers' not in cfg2
    assert _build_runner('OpenICLInferTask', args, cfg2).use_workers is None


# -- bench glue ------------------------------------------------------------

def test_bench_warm_path_child_smoke(tmp_path):
    """The bench's cold-start child prints one JSON perf record."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    r = subprocess.run(
        [sys.executable, osp.join(REPO, 'bench.py'), '--warm-path-child',
         str(tmp_path / 'xla')],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-500:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec['compile_cache_misses'] > 0
    assert rec['model_build_seconds'] > 0
