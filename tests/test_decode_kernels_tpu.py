"""Hardware parity for the decode-path Pallas kernels (slow tier).

The hermetic suite runs these kernels through the Pallas interpreter
(tests/test_decode_attention.py, tests/test_int4_kernel.py); this test
compiles them with Mosaic on the real chip — the lowering that actually
ships — and compares teacher-forced per-step decode logits (kernel
path vs XLA cache path, same int8 quantization; the forcing token is
fixed so the two runs walk identical cache states), plus the packed
stacked-weight matmul against its dequantized reference.

Same launch pattern as test_flash_tpu.py: a subprocess with the TPU
plugin env restored; skipped when no TPU is configured.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import sys
sys.path.insert(0, %(repo)r)
import dataclasses
import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == 'tpu', jax.devices()

import opencompass_tpu.nn.decode_attention as DA
from opencompass_tpu.nn import TransformerConfig, init_params
from opencompass_tpu.nn.quant import _pack_int4x2, quantize_params
from opencompass_tpu.nn import int4_matmul as im

# --- decode attention: kernel vs XLA cache path, same quantization ---
cfg = dataclasses.replace(
    TransformerConfig.llama(
        vocab_size=1024, hidden_size=512, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=1024, max_seq_len=256),
    kv_quant='int8', act_quant=True)
assert DA.supported(cfg.positional, cfg.head_dim, cfg.num_heads,
                    cfg.num_kv_heads, jnp.int8)
params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)), cfg)
rng = np.random.RandomState(0)
tokens = jnp.asarray(rng.randint(1, 1024, (4, 24)), jnp.int32)
tokens = jnp.pad(tokens, ((0, 0), (5, 0)))   # left pads
mask = tokens != 0
# teacher-forced per-step logits: both paths walk the SAME tokens, so
# the only difference is the kernel's dynamic-int8 q/p noise — token
# trajectories on a flat random-init model would diverge after any
# single flip and measure nothing
from opencompass_tpu.nn.transformer import decode_step, init_cache, prefill

def forced_logits(params, tokens, mask, nsteps):
    B, S = tokens.shape
    total = S + nsteps

    @jax.jit
    def run(params, tokens, mask):
        cache = init_cache(cfg, B, total)
        logits, cache, pos = prefill(params, cfg, tokens, mask, cache)
        kv_valid = jnp.pad(mask, ((0, 0), (0, nsteps)))
        outs = [logits]
        tok = jnp.argmax(logits, -1)
        for i in range(nsteps):
            slot = S + i
            kv_valid2 = kv_valid.at[:, slot].set(True)
            logits, cache = decode_step(params, cfg, tok, cache, slot,
                                        pos + i, kv_valid2)
            kv_valid = kv_valid2
            outs.append(logits)
            tok = jnp.argmax(outs[0], -1)  # fixed forcing token
        return jnp.stack(outs)
    return np.asarray(run(params, tokens, mask), np.float32)

lk = forced_logits(params, tokens, mask, 4)
DA.supported = lambda *a, **k: False
jax.clear_caches()
lx = forced_logits(params, tokens, mask, 4)
diff = np.abs(lk - lx)
scale = np.abs(lx).max()
print('forced logits max diff', diff.max(), 'scale', scale)
# step 0 is the prefill (identical path): must match to bf16 noise
assert diff[0].max() <= 0.05 * scale, diff[0].max()
# decode steps differ only by the kernel's int8 q/p quantization; a
# zero diff would mean the kernel path never engaged (gate drift) and
# the comparison measured nothing
assert diff[1:].max() > 0.0, 'kernel path did not engage'
assert diff[1:].max() <= 0.15 * scale, (diff[1:].max(), scale)

# --- stacked packed matmul vs dequantized reference ---
rs = np.random.RandomState(1)
L, M, O, K = 2, 16, 256, 512
packs, scales = [], []
for _ in range(L):
    w = rs.randn(K, O).astype(np.float32) * 0.05
    pw, s = _pack_int4x2(w, -2, np)
    packs.append(pw)
    scales.append(s)
wst = jnp.asarray(np.stack(packs))
sst = jnp.asarray(np.stack(scales), jnp.bfloat16)
x = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
for layer in range(L):
    y = np.asarray(jax.jit(im.packed_matmul_stacked)(
        x, wst, sst, jnp.int32(layer)), np.float32)
    pw = packs[layer]
    lo = (pw & 0xF).astype(np.int8); lo = np.where(lo > 7, lo - 16, lo)
    hi = (pw >> 4).astype(np.int8); hi = np.where(hi > 7, hi - 16, hi)
    w8 = np.concatenate([lo, hi], -1).astype(np.float32)
    sref = np.asarray(sst[layer].astype(jnp.float32))
    wf = (w8.reshape(O, K // 128, 128) * sref[..., None]).reshape(O, K)
    ref = np.asarray(x, np.float32) @ wf.T
    err = np.abs(y - ref).max()
    print('stacked matmul layer', layer, 'err', err)
    assert err < 0.02 * max(1.0, np.abs(ref).max())
print('DECODE_KERNELS_PARITY_OK')
"""


@pytest.mark.slow
def test_decode_kernels_on_tpu():
    axon = os.environ.get('OC_TPU_AXON_IPS')
    if not axon:
        pytest.skip('no TPU plugin config in environment')
    env = dict(os.environ)
    env['PALLAS_AXON_POOL_IPS'] = axon
    env.pop('JAX_PLATFORMS', None)
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run(
        [sys.executable, '-c', _SCRIPT % {'repo': REPO}],
        capture_output=True, text=True, env=env, timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'DECODE_KERNELS_PARITY_OK' in proc.stdout, proc.stdout
