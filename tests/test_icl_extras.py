"""CLP inferencer, BM25/TopK/MDL/Votek/DPP retrievers, OpenAI API model."""
import json
from unittest import mock

import numpy as np
import pytest
from datasets import Dataset, DatasetDict

from opencompass_tpu.datasets.base import BaseDataset
from opencompass_tpu.models import FakeModel


class ChoiceDS(BaseDataset):
    @staticmethod
    def load():
        rows = {
            'question': ['Is fire hot?', 'Is ice hot?'],
            'choices': [['yes', 'no'], ['yes', 'no']],
            'label': ['yes', 'no'],
        }
        train = {
            'question': ['Is the sun bright?'],
            'choices': [['yes', 'no']],
            'label': ['yes'],
        }
        return DatasetDict({'test': Dataset.from_dict(rows),
                            'train': Dataset.from_dict(train)})


def _choice_ds():
    return ChoiceDS(reader_cfg=dict(input_columns=['question'],
                                    output_column='label'))


def test_clp_inferencer_with_fake_model(tmp_path):
    from opencompass_tpu.icl import PromptTemplate, ZeroRetriever
    from opencompass_tpu.icl.inferencers import CLPInferencer
    model = FakeModel(canned_ppls={'fire hot?\nA: yes': 1.0,
                                   'ice hot?\nA: no': 1.0})
    tpl = PromptTemplate('</E>Q: {question}\nA:', ice_token='</E>')
    inferencer = CLPInferencer(model=model, batch_size=2)
    preds = inferencer.inference(ZeroRetriever(_choice_ds()),
                                 ice_template=tpl,
                                 output_json_filepath=str(tmp_path))
    assert len(preds) == 2
    for probs in preds:
        assert len(probs) == 2
        assert abs(sum(probs) - 1.0) < 1e-6
    # canned low-ppl choices dominate
    assert np.argmax(preds[0]) == 0  # yes
    assert np.argmax(preds[1]) == 1  # no
    out = json.load(open(tmp_path / 'predictions'))
    assert out['0']['choices'] == ['yes', 'no']
    assert 'pred_label' in out['0']


def test_clp_inferencer_with_jax_model(tmp_path):
    from opencompass_tpu.icl import PromptTemplate, ZeroRetriever
    from opencompass_tpu.icl.inferencers import CLPInferencer
    from opencompass_tpu.models import JaxLM
    model = JaxLM(config='tiny', max_seq_len=128)
    tpl = PromptTemplate('</E>Q: {question}\nA:', ice_token='</E>')
    inferencer = CLPInferencer(model=model, batch_size=2)
    preds = inferencer.inference(ZeroRetriever(_choice_ds()),
                                 ice_template=tpl,
                                 output_json_filepath=str(tmp_path))
    assert len(preds) == 2
    for probs in preds:
        assert abs(sum(probs) - 1.0) < 1e-3
    # deterministic across calls
    preds2 = inferencer.inference(ZeroRetriever(_choice_ds()),
                                  ice_template=tpl,
                                  output_json_filepath=str(tmp_path))
    assert np.allclose(preds, preds2)


class CorpusDS(BaseDataset):
    @staticmethod
    def load():
        train = {
            'text': ['the cat sat on the mat',
                     'quantum physics is fascinating',
                     'dogs love playing fetch',
                     'the stock market crashed today'],
            'label': ['a', 'b', 'c', 'd'],
        }
        test = {
            'text': ['a cat on a mat', 'physics of quantum systems'],
            'label': ['a', 'b'],
        }
        return DatasetDict({'train': Dataset.from_dict(train),
                            'test': Dataset.from_dict(test)})


def _corpus_ds():
    return CorpusDS(reader_cfg=dict(input_columns=['text'],
                                    output_column='label'))


def test_bm25_retriever():
    from opencompass_tpu.icl.retrievers import BM25Retriever
    retriever = BM25Retriever(_corpus_ds(), ice_num=2)
    ids = retriever.retrieve()
    assert len(ids) == 2
    assert ids[0][0] == 0  # cat/mat doc is the lexical match
    assert ids[1][0] == 1  # quantum physics doc


def test_topk_retriever_hashed_bow():
    from opencompass_tpu.icl.retrievers import TopkRetriever
    retriever = TopkRetriever(_corpus_ds(), ice_num=2)
    ids = retriever.retrieve()
    assert len(ids) == 2 and all(len(r) == 2 for r in ids)
    assert ids[0][0] == 0
    assert ids[1][0] == 1


def test_mdl_retriever_with_fake_metric():
    from opencompass_tpu.icl.retrievers import MDLRetriever
    metric = FakeModel(canned_ppls={'cat': 0.5})
    calls = []
    inner_get_ppl = metric.get_ppl
    metric.get_ppl = lambda inputs, **kw: (calls.append(len(inputs)),
                                           inner_get_ppl(inputs, **kw))[1]
    retriever = MDLRetriever(_corpus_ds(), ice_num=1, candidate_num=3,
                             select_time=3, metric_model=metric)
    ids = retriever.retrieve()
    assert len(ids) == 2 and all(len(r) == 1 for r in ids)
    # batched scoring: ONE get_ppl call per test item covering all
    # candidate orderings, not select_time unbatched device calls
    assert calls == [3, 3]


def test_votek_and_dpp_retrievers():
    from opencompass_tpu.icl.retrievers import DPPRetriever, VotekRetriever
    votek = VotekRetriever(_corpus_ds(), ice_num=2, votek_k=2)
    ids = votek.retrieve()
    assert len(ids) == 2
    assert ids[0] == ids[1]  # shared fixed set
    assert len(set(ids[0])) == 2
    dpp = DPPRetriever(_corpus_ds(), ice_num=2, candidate_num=3)
    ids = dpp.retrieve()
    assert len(ids) == 2
    for row in ids:
        assert len(set(row)) == len(row) <= 2


def test_openai_role_mapping_and_request():
    from opencompass_tpu.models.openai_api import OpenAI
    from opencompass_tpu.utils.prompt import PromptList
    model = OpenAI(path='gpt-test', key='sk-fake', query_per_second=100)
    msgs = model._to_messages(PromptList([
        dict(role='SYSTEM', prompt='be brief'),
        dict(role='HUMAN', prompt='hi'),
        dict(role='BOT', prompt='hello'),
    ]))
    assert [m['role'] for m in msgs] == ['system', 'user', 'assistant']

    response = mock.MagicMock()
    response.read.return_value = json.dumps({
        'choices': [{'message': {'content': ' pong '}}]}).encode()
    response.__enter__ = lambda s: response
    response.__exit__ = mock.MagicMock(return_value=False)
    with mock.patch('urllib.request.urlopen', return_value=response) as m:
        out = model.generate(['ping'], max_out_len=16)
    assert out == ['pong']
    sent = json.loads(m.call_args[0][0].data)
    assert sent['model'] == 'gpt-test'
    assert sent['messages'] == [{'role': 'user', 'content': 'ping'}]


def test_openai_raises_after_retry_budget():
    # a dead endpoint must fail the task, not score '' as a wrong answer
    from opencompass_tpu.models.openai_api import OpenAI
    model = OpenAI(path='gpt-test', key='sk-fake', retry=0,
                   query_per_second=100)
    with mock.patch('urllib.request.urlopen',
                    side_effect=OSError('no network')):
        with pytest.raises(RuntimeError, match='failed after 1 attempts'):
            model.generate(['ping'], max_out_len=4)
