"""Radix prefix cache over the paged KV pool + draft-model speculative
decoding (ISSUE 19).

Correctness bar: with the trie on, greedy outputs stay byte-identical
to the trie-off engine while matched rows skip prefilling the shared
prefix; with a draft model, speculative decoding stays token-identical
to the plain engine under greedy sampling and falls back cleanly
whenever its preconditions fail."""
import json
import threading

import pytest
from datasets import Dataset, DatasetDict

from opencompass_tpu.datasets.base import BaseDataset
from opencompass_tpu.icl.inferencers.gen import GenInferencer
from opencompass_tpu.icl.prompt_template import PromptTemplate
from opencompass_tpu.icl.retrievers import ZeroRetriever
from opencompass_tpu.models import JaxLM
from opencompass_tpu.nn.paged_kv import (GARBAGE_PAGE, PageAllocator,
                                         RadixPrefixCache)

READER_CFG = dict(input_columns=['question'], output_column='answer')
SHARED = 'Q: what color is the sky over the harbor at noon? A: blue. ' * 8
KW = dict(config='tiny', max_seq_len=512, continuous_batching=True,
          decode_slots=4, kv_page_size=16)


def _prompts(n, tag='item'):
    return [SHARED + f'Q: {tag} {i}? A:' for i in range(n)]


@pytest.fixture(scope='module')
def lm_plain():
    return JaxLM(**KW)


@pytest.fixture(scope='module')
def lm_cached():
    return JaxLM(prefix_cache=True, **KW)


# -- trie unit ---------------------------------------------------------------

def test_trie_match_insert_refcounts():
    """insert() adopts full-page chunks with one trie reference each;
    match() returns them retained for the caller and always leaves at
    least one suffix token unmatched."""
    alloc = PageAllocator(32)
    trie = RadixPrefixCache(alloc, 4, min_partial=2)
    ids = list(range(12))
    pages = alloc.alloc(3)
    assert trie.insert(ids, pages) == 3
    assert all(alloc.refcount(p) == 2 for p in pages)
    assert trie.insert(ids, pages) == 0          # idempotent
    assert trie.nodes == 3

    got, n, cow = trie.match(ids)
    # the exact same prompt matches 2 full pages + a partial third —
    # never all 12 tokens (the final chunk must prefill for logits)
    assert got == pages[:2] and cow == pages[2] and n == 11
    assert all(alloc.refcount(p) == 3 for p in pages)
    alloc.free(got + [cow])                       # caller's references
    assert all(alloc.refcount(p) == 2 for p in pages)
    assert trie.hits == 1 and trie.matched_tokens == 11

    got, n, cow = trie.match([99] * 12)           # no overlap
    assert got == [] and n == 0 and cow is None
    assert trie.misses == 1
    assert GARBAGE_PAGE not in pages


def test_trie_partial_match_copy_on_write_threshold():
    """A divergent chunk yields a COW source only when the common
    prefix clears ``min_partial``."""
    alloc = PageAllocator(16)
    ids_a = [1, 2, 3, 4, 5, 6, 7, 8]
    pages_a = alloc.alloc(2)
    trie = RadixPrefixCache(alloc, 4, min_partial=2)
    assert trie.insert(ids_a, pages_a) == 2

    ids_b = ids_a[:6] + [77] * 6                  # diverges mid-page-2
    got, n, cow = trie.match(ids_b)
    assert got == pages_a[:1] and n == 6 and cow == pages_a[1]
    assert alloc.refcount(pages_a[1]) == 3        # row + trie + cow ref
    alloc.free(got + [cow])

    strict = RadixPrefixCache(alloc, 4, min_partial=3)
    assert strict.insert(ids_a, pages_a) == 2     # its own references
    got, n, cow = strict.match(ids_b)
    assert got == pages_a[:1] and n == 4 and cow is None
    alloc.free(got)


def test_trie_evict_lru_spares_shared_pages():
    """evict() frees cold leaves whose only reference is the trie's;
    pages a live row still maps are never touched."""
    alloc = PageAllocator(16)
    trie = RadixPrefixCache(alloc, 4)
    ids_a, ids_b = [1] * 8, [2] * 8
    pages_a, pages_b = alloc.alloc(2), alloc.alloc(2)
    trie.insert(ids_a, pages_a)
    trie.insert(ids_b, pages_b)
    alloc.free(pages_a)                           # row A retired
    assert trie.evict(10) == 2                    # A's leaf, then head
    assert trie.nodes == 2 and trie.evicted_pages == 2
    assert all(alloc.refcount(p) == 0 for p in pages_a)
    assert all(alloc.refcount(p) == 2 for p in pages_b)
    alloc.free(pages_b)                           # row B retired
    assert trie.evict(10) == 2
    assert alloc.n_allocated == 0 and trie.nodes == 0


# -- engine: prefix cache ----------------------------------------------------

def test_engine_prefix_cache_identity_and_savings(lm_plain, lm_cached):
    """>=70%-shared workload: the trie halves prefill tokens (ISSUE
    floor) while outputs stay byte-identical; a second drain reuses the
    warm trie; retired rows leave only the trie's own references."""
    prompts = _prompts(12)
    eng_off = lm_plain.continuous_engine()
    p0 = eng_off.prefill_tokens
    ref = lm_plain.generate_continuous(prompts, 6)
    off_prefill = eng_off.prefill_tokens - p0

    stats_out = {}
    out = lm_cached.generate_continuous(prompts, 6, stats_out=stats_out)
    assert out == ref
    engine = lm_cached.continuous_engine()
    st = engine.stats()
    assert st['prefix_cache_enabled'] and st['prefix_hits'] > 0
    assert st['prefill_tokens_saved'] > 0
    assert stats_out['prefill_tokens_saved'] == st['prefill_tokens_saved']
    assert engine.prefill_tokens <= 0.5 * off_prefill
    assert st['prefix_cache']['nodes'] > 0
    # every page still allocated after the drain is a trie reference
    assert engine.alloc.n_allocated == engine.prefix.nodes

    out2 = lm_cached.generate_continuous(prompts, 6)   # warm trie
    assert out2 == ref
    st2 = engine.stats()
    assert st2['prefill_tokens_saved'] > st['prefill_tokens_saved']
    assert lm_cached.continuous_plan()['prefix_cache'] is True
    assert 'prefix_cache' not in lm_plain.continuous_plan()


def test_engine_prefix_eviction_under_pool_pressure():
    """Distinct prefixes overflow a small pool: admission evicts cold
    trie pages instead of failing, and outputs stay correct."""
    kw = dict(config='tiny', max_seq_len=128, continuous_batching=True,
              decode_slots=2, kv_page_size=16)
    prompts = ['row %d ' % i
               + ' '.join('w%d_%d' % (i, j) for j in range(28)) + ' ?'
               for i in range(8)]
    ref = JaxLM(**kw).generate_continuous(prompts, 4)
    lm = JaxLM(prefix_cache=True, **kw)
    assert lm.generate_continuous(prompts, 4) == ref
    engine = lm.continuous_engine()
    assert engine.prefix.evicted_pages > 0
    assert engine.alloc.n_allocated == engine.prefix.nodes


def test_concurrent_interactive_rows_share_pages(lm_plain, lm_cached):
    """A second thread's interactive rows join the cached engine
    mid-drain and hit the same trie pages the sweep rows map — sibling
    outputs stay uncorrupted on both sides."""
    sweep_prompts = _prompts(10, 'sweep')
    inter_prompts = _prompts(2, 'join')
    ref_sweep = lm_plain.generate_continuous(sweep_prompts, 8)
    ref_inter = lm_plain.generate_continuous(inter_prompts, 8)

    engine = lm_cached.continuous_engine()
    hits0 = engine.prefix.hits
    results = {}
    started = threading.Event()

    def sweep():
        def on_result(i, text):
            started.set()
            results[i] = text
        results['sweep'] = lm_cached.generate_continuous(
            sweep_prompts, 8, on_result=on_result)

    thread = threading.Thread(target=sweep)
    thread.start()
    try:
        assert started.wait(60)
        ids = [lm_cached._encode_ids(p) for p in inter_prompts]
        rows = [engine.submit(r, 8, tag=k, interactive=True)
                for k, r in enumerate(ids)]
        inter_out = [None, None]

        def deliver(row):
            toks = [t for t in row.emitted
                    if t != lm_cached.eos_token_id]
            inter_out[row.tag] = lm_cached.tokenizer.decode(toks)

        engine.drain(rows, deliver, timeout=120)
    finally:
        thread.join(120)
    assert results['sweep'] == ref_sweep
    assert inter_out == ref_inter
    assert engine.prefix.hits > hits0
    assert engine.alloc.n_allocated == engine.prefix.nodes


# -- engine: speculative decoding --------------------------------------------

def test_speculative_identity_and_accept_rate(lm_plain):
    """Draft-model speculative decoding emits token-identical greedy
    output (every emitted token is the target's verify-lane argmax) and
    surfaces its acceptance rate per drain."""
    prompts = _prompts(6)
    ref = lm_plain.generate_continuous(prompts, 12)
    lm = JaxLM(draft_model=dict(config='tiny', max_seq_len=512),
               draft_k=4, **KW)
    assert lm.speculative_eligible and lm.speculative_active
    stats_out = {}
    out = lm.generate_continuous(prompts, 12, stats_out=stats_out)
    assert out == ref
    engine = lm.continuous_engine()
    assert engine.spec and engine.spec_k == 4
    st = engine.stats()
    assert st['speculative'] and st['spec_proposed'] > 0
    assert st['spec_accepted'] <= st['spec_proposed']
    assert 0.0 < st['spec_accept_rate'] <= 1.0
    assert stats_out['spec_accept_rate'] == st['spec_accept_rate']
    plan = lm.continuous_plan()['speculative']
    assert plan == {'draft_k': 4, 'eligible': True, 'verify_shape': '4x5'}


def test_speculative_fallback_pins():
    """Every precondition failure degrades to the plain engine path —
    never an error: no draft config, draft_k < 1, stochastic sampling,
    and a draft without resident params."""
    base = dict(config='tiny', max_seq_len=256, tokenizer_only=True,
                continuous_batching=True, decode_slots=2,
                kv_page_size=16)
    draft = dict(config='tiny', tokenizer_only=True)
    assert not JaxLM(**base).speculative_eligible
    assert not JaxLM(draft_model=draft, draft_k=0,
                     **base).speculative_eligible
    assert not JaxLM(draft_model=draft,
                     generation_kwargs=dict(do_sample=True,
                                            temperature=0.7),
                     **base).speculative_eligible
    lm = JaxLM(draft_model=draft, **base)
    assert lm.speculative_eligible           # device-free gate passes
    assert not lm.speculative_active         # ...but no resident params
    assert 'speculative' not in JaxLM(**base).continuous_plan()


# -- store: kill/resume with a warm trie -------------------------------------

class SharedPrefixDataset(BaseDataset):
    @staticmethod
    def load(n_test=10):
        ctx = ('the harbor master logs every vessel arriving before '
               'noon and files a daily report with the port '
               'authority. ') * 3
        rows = [{'question': ctx + f'what is log entry {i}?',
                 'answer': 'A'} for i in range(n_test)]
        return DatasetDict({'train': Dataset.from_list(rows[:2]),
                            'test': Dataset.from_list(rows)})


class _CrashAfterLM(JaxLM):
    """Delivers N rows through the continuous path, then dies with the
    radix trie warm and shared pages mapped by in-flight rows."""

    def __init__(self, crash_after, **kw):
        super().__init__(**kw)
        self.crash_after = crash_after

    def generate_continuous(self, inputs, max_out_len, on_result=None,
                            **kw):
        delivered = [0]

        def wrapped(i, text):
            if delivered[0] >= self.crash_after:
                raise KeyboardInterrupt('injected mid-engine kill')
            delivered[0] += 1
            if on_result is not None:
                on_result(i, text)
        return super().generate_continuous(inputs, max_out_len,
                                           on_result=wrapped, **kw)


def test_kill_resume_with_shared_pages(tmp_path, monkeypatch):
    """Mid-sweep kill while trie pages are shared across live rows:
    committed rows survive in the store, the restart recomputes only
    the missing rows, converges bit-identical to a clean run, and
    leaves zero duplicate store keys."""
    from opencompass_tpu import store as S
    kw = dict(config='tiny', max_seq_len=512, continuous_batching=True,
              decode_slots=2, kv_page_size=16, prefix_cache=True)
    model_cfg = {'type': 'JaxLM', 'path': 'tiny-prefix',
                 'config': 'tiny'}
    ds = SharedPrefixDataset(reader_cfg=READER_CFG)
    template = PromptTemplate('Q: {question}\nA: {answer}')

    def bound(model):
        S.bind_model_store(model, model_cfg)
        return model

    def infer(sub, model):
        inf = GenInferencer(model=model, max_out_len=5, batch_size=4,
                            output_json_filepath=str(tmp_path / sub),
                            batch_plan=True)
        return inf.inference(ZeroRetriever(ds),
                             prompt_template=template)

    ref_cache = str(tmp_path / 'cache_ref')
    monkeypatch.setenv('OCT_CACHE_ROOT', ref_cache)
    S.reset_stores()
    ref = infer('ref', bound(JaxLM(**kw)))

    cache_root = str(tmp_path / 'cache')
    monkeypatch.setenv('OCT_CACHE_ROOT', cache_root)
    S.reset_stores()
    with pytest.raises(KeyboardInterrupt):
        infer('crash', bound(_CrashAfterLM(3, **kw)))

    S.reset_stores()
    resumed = bound(JaxLM(**kw))
    out = infer('resume', resumed)
    assert out == ref
    assert resumed.perf.samples == 10 - 3    # only the missing rows
    verdict = S.open_store().verify()
    assert verdict['ok'] and verdict['duplicate_keys'] == 0
    assert verdict['rows'] == 10


# -- observability: rollup, doctor, plan -------------------------------------

def test_timeline_rollup_prefix_and_spec():
    from opencompass_tpu.obs.timeline import summarize_records
    recs = [
        {'t': 'engine', 'prefix_cache_enabled': True,
         'prefix_shareable_frac': 0.74, 'prefill_tokens': 300,
         'prefill_tokens_saved': 700, 'spec_proposed': 40,
         'spec_accepted': 30},
        {'t': 'engine', 'prefix_cache_enabled': True,
         'prefix_shareable_frac': 0.5, 'prefill_tokens': 100,
         'prefill_tokens_saved': 100, 'spec_proposed': 10,
         'spec_accepted': 10},
    ]
    s = summarize_records(recs)
    assert s['prefix_cache_enabled'] is True
    assert s['prefix_shareable_frac'] == 0.74
    assert s['prefill_tokens_saved'] == 800
    assert s['spec_accept_rate'] == 0.8
    empty = summarize_records([])
    assert empty['prefix_cache_enabled'] is None
    assert empty['spec_accept_rate'] is None


def test_doctor_prefix_waste_rule():
    """warn when a high-share sweep ran with the cache off, info when
    the cache is on but never hits, silent when healthy or when the
    census says there is nothing to share."""
    from opencompass_tpu.obs.doctor import _rule_prefix_waste

    def art(**kw):
        base = dict(prefill_tokens=1000)
        base.update(kw)
        return {'timelines': {'task': base}}

    f = _rule_prefix_waste(art(prefix_shareable_frac=0.8,
                               prefill_tokens_saved=0,
                               prefix_cache_enabled=False))
    assert [x['severity'] for x in f] == ['warn']
    assert f[0]['rule'] == 'prefix_waste' and 'prefix_cache=True' \
        in f[0]['fix']
    f = _rule_prefix_waste(art(prefix_shareable_frac=0.8,
                               prefill_tokens_saved=10,
                               prefix_cache_enabled=True))
    assert [x['severity'] for x in f] == ['info']
    assert _rule_prefix_waste(art(prefix_shareable_frac=0.8,
                                  prefill_tokens_saved=900,
                                  prefix_cache_enabled=True)) == []
    assert _rule_prefix_waste(art(prefix_shareable_frac=0.1,
                                  prefill_tokens_saved=0,
                                  prefix_cache_enabled=False)) == []
    assert _rule_prefix_waste(art()) == []


def test_plan_preview_reports_prefix_reuse(tmp_path):
    """`cli plan` pre-flight: the continuous block carries the expected
    trie reuse — census prefix share x rows -> est. prefill tokens and
    pages saved (device-free; tokenizer_only)."""
    ds = SharedPrefixDataset(reader_cfg=READER_CFG)
    template = PromptTemplate('Q: {question}\nA: {answer}')
    lm = JaxLM(config='tiny', max_seq_len=512, tokenizer_only=True,
               continuous_batching=True, decode_slots=4,
               kv_page_size=16, prefix_cache=True)
    inf = GenInferencer(model=lm, max_out_len=5, batch_size=4,
                        output_json_filepath=str(tmp_path / 'plan'),
                        batch_plan=True)
    preview = inf.plan_preview(ZeroRetriever(ds),
                               prompt_template=template)
    cont = preview['continuous']
    assert cont['prefix_cache'] is True
    reuse = cont['prefix_reuse']
    census = preview['prefix']
    assert reuse['est_prefill_tokens_saved'] == \
        census['prefix_tokens'] * (cont['rows'] - 1)
    assert reuse['est_pages_saved'] == \
        (census['prefix_tokens'] // 16) * (cont['rows'] - 1)
    assert 0.0 < reuse['est_saved_frac'] <= 1.0
    assert json.dumps(preview)               # stays JSON-serializable
