"""CompletionsAPI: generation and echo-logprob PPL over a mocked
OpenAI-compatible /v1/completions endpoint."""
import io
import json

import numpy as np
import pytest

from opencompass_tpu.models import CompletionsAPI


class _FakeResponse:
    def __init__(self, payload):
        self._data = json.dumps(payload).encode()

    def read(self):
        return self._data

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def _patch_endpoint(monkeypatch, handler):
    def fake_urlopen(request, timeout=None):
        body = json.loads(request.data)
        return _FakeResponse(handler(body))
    monkeypatch.setattr('urllib.request.urlopen', fake_urlopen)


def test_generate(monkeypatch):
    def handler(body):
        assert body['model'] == 'opt-175b'
        assert body['max_tokens'] == 16
        return {'choices': [{'text': f" -> completion of {body['prompt']}"}]}
    _patch_endpoint(monkeypatch, handler)
    m = CompletionsAPI(path='opt-175b', url='http://x/v1/completions',
                       key='', query_per_second=1000)
    out = m.generate(['a', 'b'], max_out_len=16)
    assert out == [' -> completion of a', ' -> completion of b']


def test_get_ppl_echo_logprobs(monkeypatch):
    def handler(body):
        assert body == {'model': 'm', 'prompt': body['prompt'],
                        'max_tokens': 0, 'echo': True, 'logprobs': 0}
        # 4 tokens: first logprob is null (no conditional), then 3 values
        return {'choices': [{'logprobs': {
            'token_logprobs': [None, -1.0, -2.0, -3.0]}}]}
    _patch_endpoint(monkeypatch, handler)
    m = CompletionsAPI(path='m', url='http://x', key='',
                       query_per_second=1000)
    ppl = m.get_ppl(['some text'])
    np.testing.assert_allclose(ppl, [2.0])
    # mask_length counts come from the heuristic client tokenizer and
    # cannot map onto server BPE logprobs — must refuse, not skew scores
    with pytest.raises(NotImplementedError):
        m.get_ppl(['some text'], mask_length=[2])


def test_ppl_inferencer_over_completions_api(monkeypatch, tmp_path):
    """The ranking path works end-to-end over an API-served base model."""
    from opencompass_tpu.datasets.base import BaseDataset
    from opencompass_tpu.icl import PromptTemplate
    from opencompass_tpu.icl.inferencers import PPLInferencer
    from opencompass_tpu.icl.retrievers import ZeroRetriever
    from datasets import Dataset, DatasetDict

    def handler(body):
        # favor prompts ending in 'B': higher logprobs -> lower ppl
        good = str(body['prompt']).strip().endswith('B')
        lp = -0.1 if good else -5.0
        return {'choices': [{'logprobs': {
            'token_logprobs': [None, lp, lp, lp]}}]}
    _patch_endpoint(monkeypatch, handler)

    class _Toy(BaseDataset):
        @staticmethod
        def load():
            rows = [{'q': f'q{i}', 'a': 'B'} for i in range(2)]
            return DatasetDict({'train': Dataset.from_list(rows),
                                'test': Dataset.from_list(rows)})

    ds = _Toy(reader_cfg=dict(input_columns=['q'], output_column='a'))
    m = CompletionsAPI(path='m', url='http://x', key='',
                       query_per_second=1000)
    inf = PPLInferencer(model=m, batch_size=2,
                        output_json_filepath=str(tmp_path))
    tmpl = PromptTemplate({'A': 'Q: {q}\nA: A', 'B': 'Q: {q}\nA: B'})
    preds = inf.inference(ZeroRetriever(ds), prompt_template=tmpl)
    assert preds == ['B', 'B']


def test_choice_via_echo_logprobs(monkeypatch):
    def handler(body):
        # higher logprobs when the prompt ends with ' right'
        good = str(body['prompt']).endswith(' right')
        lp = -0.5 if good else -4.0
        n_tok = len(str(body['prompt']).split())
        return {'choices': [{'logprobs': {
            'token_logprobs': [None] + [lp] * n_tok}}]}
    _patch_endpoint(monkeypatch, handler)
    m = CompletionsAPI(path='m', url='http://x', key='',
                       query_per_second=1000)
    out = m.choice(['the answer is', 'pick'], [' right', ' wrong'])
    assert out == [' right', ' right']
