"""Orchestration: partitioners, runners, tasks, summarizer, run.py CLI."""
import json
import os
import os.path as osp
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


def _demo_cfg(work_dir, models=None):
    from opencompass_tpu.config import Config
    cfg = Config.fromfile(osp.join(REPO, 'configs/eval_demo.py'))
    cfg['work_dir'] = str(work_dir)
    if models is not None:
        cfg['models'] = models
    return cfg


def test_naive_partitioner_skips_existing(tmp_path):
    from opencompass_tpu.partitioners import NaivePartitioner
    cfg = _demo_cfg(tmp_path)
    out_dir = str(tmp_path / 'predictions')
    part = NaivePartitioner(out_dir)
    tasks = part(cfg)
    assert len(tasks) == 2  # 1 model × 2 datasets
    # simulate one output existing → one task disappears
    done = tasks[0]['datasets'][0][0]
    from opencompass_tpu.utils.abbr import get_infer_output_path
    path = get_infer_output_path(tasks[0]['models'][0], done, out_dir)
    os.makedirs(osp.dirname(path), exist_ok=True)
    with open(path, 'w') as f:
        f.write('{}')
    assert len(part(cfg)) == 1


def test_size_partitioner_splits_and_packs(tmp_path):
    from opencompass_tpu.partitioners import SizePartitioner
    cfg = _demo_cfg(tmp_path)
    part = SizePartitioner(str(tmp_path / 'predictions'),
                           max_task_size=100, gen_task_coef=20,
                           dataset_size_path=str(tmp_path / 'size.json'))
    tasks = part(cfg)
    # demo-gen: 16 rows × 20 = 320 → split into ceil(16/5)=4 shards;
    # demo-ppl: 8 rows × 2 labels = 16 → one small task
    split_abbrs = [ds['abbr'] for t in tasks for ds in t['datasets'][0]]
    assert sum(a.startswith('demo-gen_') for a in split_abbrs) == 4
    assert 'demo-ppl' in split_abbrs
    ranges = [ds['reader_cfg']['test_range'] for t in tasks
              for ds in t['datasets'][0] if ds['abbr'].startswith('demo-gen')]
    assert ranges[0] == '[0:5]'
    # size cache persisted
    assert json.loads((tmp_path / 'size.json').read_text())['demo-gen'] == 16


def test_size_partitioner_cost_model():
    from opencompass_tpu.partitioners import SizePartitioner
    part = SizePartitioner('/nonexistent', gen_task_coef=20)
    gen_cfg = {'infer_cfg': {'inferencer': {'type': 'GenInferencer'},
                             'prompt_template': {'template': 'x'}}}
    ppl_cfg = {'infer_cfg': {'inferencer': {'type': 'PPLInferencer'},
                             'prompt_template': {'template': {'A': 'a',
                                                              'B': 'b',
                                                              'C': 'c'}}}}
    assert part.get_factor(gen_cfg) == 20
    assert part.get_factor(ppl_cfg) == 3


def _run_cli(args, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, 'run.py', *args], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=240)


@pytest.mark.slow
def test_run_cli_end_to_end_with_resume(tmp_path):
    work = str(tmp_path / 'out')
    r = _run_cli(['configs/eval_demo.py', '-w', work,
                  '--max-num-workers', '2'])
    assert r.returncode == 0, r.stdout + r.stderr
    run_dirs = [d for d in os.listdir(work) if d != 'cache']
    assert len(run_dirs) == 1
    root = osp.join(work, run_dirs[0])
    assert osp.exists(osp.join(root, 'predictions/fake-demo/demo-gen.json'))
    assert osp.exists(osp.join(root, 'results/fake-demo/demo-ppl.json'))
    summary = [f for f in os.listdir(osp.join(root, 'summary'))
               if f.endswith('.txt')]
    assert summary
    text = open(osp.join(root, 'summary', summary[0])).read()
    assert 'demo-gen' in text and 'demo-ppl' in text

    # resume: everything exists → both phases skip, same summary
    r2 = _run_cli(['configs/eval_demo.py', '-w', work, '-r'])
    assert r2.returncode == 0
    assert 'skipping infer' in r2.stdout + r2.stderr
    assert 'skipping eval' in r2.stdout + r2.stderr


@pytest.mark.slow
def test_run_cli_size_split_stitching(tmp_path):
    """Oversized dataset → _k prediction shards → eval stitches them."""
    work = str(tmp_path / 'out')
    r = _run_cli(['configs/eval_demo.py', '-w', work,
                  '--max-partition-size', '100', '--debug'])
    assert r.returncode == 0, r.stdout + r.stderr
    root = osp.join(work, [d for d in os.listdir(work)
                           if d != 'cache'][0])
    shards = [f for f in os.listdir(osp.join(root, 'predictions/fake-demo'))
              if f.startswith('demo-gen_')]
    assert len(shards) == 4
    result = json.load(open(osp.join(root,
                                     'results/fake-demo/demo-gen.json')))
    assert 'score' in result


def test_summarizer_groups(tmp_path):
    from opencompass_tpu.utils.summarizer import Summarizer
    cfg = _demo_cfg(tmp_path)
    cfg['summarizer'] = {
        'summary_groups': [
            {'name': 'demo-avg', 'subsets': ['demo-gen', 'demo-ppl']},
            {'name': 'demo-weighted',
             'subsets': ['demo-gen', 'demo-ppl'],
             'weights': {'demo-gen': 3, 'demo-ppl': 1}},
        ]
    }
    res_dir = tmp_path / 'results' / 'fake-demo'
    res_dir.mkdir(parents=True)
    (res_dir / 'demo-gen.json').write_text('{"score": 80.0}')
    (res_dir / 'demo-ppl.json').write_text('{"accuracy": 40.0}')
    table = Summarizer(cfg).summarize('t')
    assert 'demo-avg' in table
    lines = {l.split()[0]: l for l in table.splitlines() if l.strip()}
    assert '60.00' in lines['demo-avg']          # (80+40)/2
    assert '70.00' in lines['demo-weighted']     # (3*80+40)/4


def test_summarizer_version_column_tracks_prompt_changes(tmp_path):
    """Two runs whose prompts differ must show different 'version' hashes
    (reference utils/summarizer.py:134 parity)."""
    from opencompass_tpu.utils.summarizer import Summarizer

    def cfg_with_prompt(prompt):
        cfg = _demo_cfg(tmp_path)
        for ds in cfg['datasets']:
            tpl = ds['infer_cfg']['prompt_template']
            if isinstance(tpl.get('template'), str):
                tpl['template'] = prompt
        return cfg

    res_dir = tmp_path / 'results' / 'fake-demo'
    res_dir.mkdir(parents=True, exist_ok=True)
    (res_dir / 'demo-gen.json').write_text('{"score": 80.0}')

    t1 = Summarizer(cfg_with_prompt('Q: {question}\nA: ')).summarize('v1')
    t2 = Summarizer(cfg_with_prompt('Answer now!\n{question}')).summarize(
        'v2')

    def version_of(table):
        for line in table.splitlines():
            if line.startswith('demo-gen'):
                return line.split()[1]
        raise AssertionError(table)

    v1, v2 = version_of(t1), version_of(t2)
    assert v1 != v2
    assert len(v1) == 6


def test_eval_task_pred_role_extraction(tmp_path):
    from opencompass_tpu.tasks.openicl_eval import extract_role_pred
    s = '<sys>ignored</sys><bot>The answer</bot>trailing'
    assert extract_role_pred(s, '<bot>', '</bot>') == 'The answer'
    assert extract_role_pred(s, None, None) == s
    assert extract_role_pred(s, '<missing>', '</bot>') == \
        '<sys>ignored</sys><bot>The answer'


def test_local_runner_watchdog_kills_hung_task(tmp_path):
    from opencompass_tpu.runners import LocalRunner
    r = LocalRunner(task=dict(type='OpenICLInferTask'),
                    stall_timeout=2, retry=0)
    log = tmp_path / 'hung.out'
    # a command that writes once then hangs silently
    rc = r._run_once('echo started && sleep 60', dict(os.environ),
                     str(log), 'hung-task')
    assert rc == -9
    assert 'started' in log.read_text()


def test_local_runner_timeout_kills_task(tmp_path):
    from opencompass_tpu.runners import LocalRunner
    r = LocalRunner(task=dict(type='OpenICLInferTask'), task_timeout=2)
    rc = r._run_once('sleep 60', dict(os.environ),
                     str(tmp_path / 't.out'), 'slow-task')
    assert rc == -9


def test_local_runner_fast_task_unaffected(tmp_path):
    from opencompass_tpu.runners import LocalRunner
    r = LocalRunner(task=dict(type='OpenICLInferTask'),
                    task_timeout=30, stall_timeout=30)
    rc = r._run_once('echo ok', dict(os.environ),
                     str(tmp_path / 'f.out'), 'fast-task')
    assert rc == 0


def test_slot_allocator_thread_safety():
    """Hammer the chip-slot allocator from many threads: no slot may ever
    be double-assigned, and all slots return free at the end (the lock
    around the slot array is the framework's only GPU/TPU-slot race
    guard — cf. reference runners/local.py:60-92)."""
    from opencompass_tpu.runners import LocalRunner
    r = LocalRunner(task=dict(type='OpenICLInferTask'), num_devices=4)
    in_use, errors = set(), []
    guard = threading.Lock()

    def worker(_):
        for _ in range(25):
            ids = r._acquire_slots(1 + _ % 2)
            with guard:
                for i in ids:
                    if i in in_use:
                        errors.append(f'slot {i} double-assigned')
                    in_use.add(i)
            time.sleep(0.001)
            with guard:
                for i in ids:
                    in_use.discard(i)
            r._release_slots(ids)

    with ThreadPoolExecutor(max_workers=8) as pool:
        list(pool.map(worker, range(8)))
    assert not errors, errors[:3]
    assert r._slots == [False] * 4


def test_cli_config_declared_runner():
    """cfg[phase].runner (reference run.py semantics) builds the runner;
    CLI flags fill unset defaults and launcher flags override."""
    import types

    from opencompass_tpu.cli import _build_runner
    args = types.SimpleNamespace(slurm=False, dlc=False, debug=True,
                                 max_num_workers=4, partition=None,
                                 quotatype=None, retry=0, num_devices=None)
    cfg = {'infer': {'runner': dict(type='LocalRunner', max_num_workers=2,
                                    retry=3, stall_timeout=900)}}
    r = _build_runner('OpenICLInferTask', args, cfg, phase='infer')
    assert type(r).__name__ == 'LocalRunner'
    assert (r.max_num_workers, r.retry, r.stall_timeout) == (2, 3, 900)
    assert r.debug is True  # CLI default filled in
    # phase without a config runner falls back to CLI construction
    r2 = _build_runner('OpenICLEvalTask', args, cfg, phase='eval')
    assert r2.max_num_workers == 4
    # an explicit launcher flag overrides the config runner
    args.slurm = True
    r3 = _build_runner('OpenICLInferTask', args, cfg, phase='infer')
    assert type(r3).__name__ == 'SlurmRunner'
